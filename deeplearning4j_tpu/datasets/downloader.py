"""HTTP dataset download + cache + checksum verification.

Parity: reference ``base/MnistFetcher.java:43-54`` — ``downloadAndUntar``
fetches the canonical archives into ``~/.deeplearning4j`` and is invoked
lazily by the data fetchers when local files are absent.

Design: mirror lists per file (primary + alternates), streaming download to
a temp file, optional sha256 verification, atomic rename into the cache dir.
Zero-egress environments simply get ``None`` back (offline-safe: fetchers
fall through to their synthetic surrogates). ``DL4J_TPU_AUTO_DOWNLOAD=0``
disables network attempts entirely.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import urllib.error
import urllib.parse
import urllib.request
from pathlib import Path
from typing import Dict, Iterable, Optional, Sequence

DEFAULT_TIMEOUT = float(os.environ.get("DL4J_TPU_DOWNLOAD_TIMEOUT", "15"))

# Hosts that already failed this process — never re-attempted, so offline
# (zero-egress) environments pay each unreachable mirror's timeout at most
# once per run instead of once per iterator construction.
_failed_hosts: set = set()


def _host(url: str) -> str:
    return urllib.parse.urlsplit(url).netloc


def auto_download_enabled() -> bool:
    return os.environ.get("DL4J_TPU_AUTO_DOWNLOAD", "1") != "0"


def sha256_of(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download_file(urls: Sequence[str], dest: Path, *,
                  sha256: Optional[str] = None,
                  timeout: float = DEFAULT_TIMEOUT) -> Optional[Path]:
    """Fetch the first working mirror into ``dest`` (atomic). Returns the
    path, or None if every mirror fails / network is unavailable. An existing
    file that passes the checksum is reused without touching the network."""
    dest = Path(dest)
    if dest.exists():
        if sha256 is None or sha256_of(dest) == sha256:
            return dest
        dest.unlink()  # corrupt/partial cache entry
    if not auto_download_enabled():
        return None
    dest.parent.mkdir(parents=True, exist_ok=True)
    for url in urls:
        if _host(url) in _failed_hosts:
            continue
        tmp = None
        try:
            fd, tmp = tempfile.mkstemp(dir=str(dest.parent),
                                       prefix=dest.name + ".part")
            # own the fd via fdopen BEFORE urlopen can raise, so failed
            # mirrors never leak descriptors
            with os.fdopen(fd, "wb") as out, \
                    urllib.request.urlopen(url, timeout=timeout) as r:
                while True:
                    chunk = r.read(1 << 20)
                    if not chunk:
                        break
                    out.write(chunk)
            if sha256 is not None and sha256_of(Path(tmp)) != sha256:
                raise IOError(f"checksum mismatch for {url}")
            os.replace(tmp, dest)
            return dest
        except Exception:
            _failed_hosts.add(_host(url))
            if tmp is not None and os.path.exists(tmp):
                os.unlink(tmp)
            continue
    return None


# ----------------------------------------------------------------------
# dataset manifests (canonical + mirror URLs; checksums of the canonical
# archives where stable)
# ----------------------------------------------------------------------

MNIST_BASE_URLS = (
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "http://yann.lecun.com/exdb/mnist/",   # the reference's canonical host
)

MNIST_FILES: Dict[str, str] = {
    "train-images-idx3-ubyte.gz":
        "440fcabf73cc546fa21475e81ea370265605f56be210a4024d2ca8f203523609",
    "train-labels-idx1-ubyte.gz":
        "3552534a0a558bbed6aed32b30c495cca23d567ec52cac8be1a0730e8010255c",
    "t10k-images-idx3-ubyte.gz":
        "8d422c7b0a1c1c79245a5bcf07fe86e33eeafee792b84584aec276f5a2dbc4e6",
    "t10k-labels-idx1-ubyte.gz":
        "f7ae60f92e00ec6debd23a6088c31dbd2371eca3ffa0defaefb259924204aec6",
}

CIFAR10_URLS = (
    "https://www.cs.toronto.edu/~kriz/cifar-10-binary.tar.gz",
)
CIFAR10_SHA256 = \
    "c4a38c50a1bc5f3a1c5537f2155ab9d68f9f25eb1ed8d9ddda3db29a59bca1dd"


def fetch_mnist(cache_dir: Optional[Path] = None,
                base_urls: Iterable[str] = MNIST_BASE_URLS,
                checksums: Optional[Dict[str, str]] = MNIST_FILES,
                ) -> Optional[Path]:
    """Download the four MNIST idx archives into the cache; returns the cache
    dir if all four are present afterwards, else None."""
    cache = Path(cache_dir) if cache_dir else Path.home() / ".cache" / "mnist"
    names = (checksums or MNIST_FILES).keys()
    ok = True
    for name in names:
        sha = checksums.get(name) if checksums else None
        urls = [b.rstrip("/") + "/" + name for b in base_urls]
        if download_file(urls, cache / name, sha256=sha) is None:
            ok = False
    return cache if ok else None


def fetch_cifar10(cache_dir: Optional[Path] = None,
                  urls: Iterable[str] = CIFAR10_URLS,
                  sha256: Optional[str] = CIFAR10_SHA256) -> Optional[Path]:
    """Download + extract the CIFAR-10 binary batches; returns the directory
    holding data_batch_*.bin, else None."""
    import tarfile

    cache = Path(cache_dir) if cache_dir else Path.home() / ".cache" / "cifar10"
    marker = cache / "cifar-10-batches-bin" / "data_batch_1.bin"
    if marker.exists():
        return marker.parent
    archive = download_file(list(urls), cache / "cifar-10-binary.tar.gz",
                            sha256=sha256)
    if archive is None:
        return None
    try:
        with tarfile.open(archive) as tf:
            tf.extractall(cache, filter="data")
    except TypeError:  # python < 3.12 lacks the filter kwarg
        with tarfile.open(archive) as tf:
            tf.extractall(cache)
    return marker.parent if marker.exists() else None
