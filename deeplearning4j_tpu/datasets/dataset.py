"""DataSet: features + labels (+ masks) container.

Parity: ND4J's ``DataSet`` (external to the reference tree but its API is the
currency of every ``fit``/iterator signature: ``getFeatures``, ``getLabels``,
``splitTestAndTrain``, ``shuffle``, ``batchBy``).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import numpy as np


class SplitTestAndTrain(NamedTuple):
    train: "DataSet"
    test: "DataSet"


def _as_array(x):
    """Keep device (jax) arrays as-is; coerce lists/scalars to numpy."""
    if x is None or hasattr(x, "shape"):
        return x
    return np.asarray(x)


class DataSet:
    def __init__(self, features, labels,
                 features_mask=None, labels_mask=None):
        self.features = _as_array(features)
        self.labels = _as_array(labels)
        self.features_mask = _as_array(features_mask)
        self.labels_mask = _as_array(labels_mask)

    def num_examples(self) -> int:
        return self.features.shape[0]

    def get_features(self) -> np.ndarray:
        return self.features

    def get_labels(self) -> np.ndarray:
        return self.labels

    def _take(self, idx) -> "DataSet":
        return DataSet(
            self.features[idx], self.labels[idx],
            None if self.features_mask is None else self.features_mask[idx],
            None if self.labels_mask is None else self.labels_mask[idx])

    def shuffle(self, seed: Optional[int] = None) -> None:
        order = np.random.default_rng(seed).permutation(self.num_examples())
        self.features = self.features[order]
        self.labels = self.labels[order]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[order]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[order]

    def split_test_and_train(self, fraction_or_count) -> SplitTestAndTrain:
        """Split off the first `n` (or fraction) examples as train, rest test
        (parity: ``DataSet.splitTestAndTrain``)."""
        n = self.num_examples()
        k = (int(round(n * fraction_or_count))
             if isinstance(fraction_or_count, float) else int(fraction_or_count))
        k = max(0, min(n, k))
        return SplitTestAndTrain(self._take(slice(0, k)), self._take(slice(k, n)))

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        return [self._take(slice(i, i + batch_size))
                for i in range(0, self.num_examples(), batch_size)]

    def sample(self, n: int, seed: Optional[int] = None,
               with_replacement: bool = True) -> "DataSet":
        rng = np.random.default_rng(seed)
        idx = rng.choice(self.num_examples(), size=n, replace=with_replacement)
        return self._take(idx)

    @staticmethod
    def merge(datasets: List["DataSet"]) -> "DataSet":
        feats = np.concatenate([d.features for d in datasets], axis=0)
        labels = np.concatenate([d.labels for d in datasets], axis=0)
        fm = (np.concatenate([d.features_mask for d in datasets], axis=0)
              if datasets and datasets[0].features_mask is not None else None)
        lm = (np.concatenate([d.labels_mask for d in datasets], axis=0)
              if datasets and datasets[0].labels_mask is not None else None)
        return DataSet(feats, labels, fm, lm)

    def scale_min_max(self, lo: float = 0.0, hi: float = 1.0) -> None:
        """Min-max normalize features in place (parity: DataSet.scaleMinAndMax)."""
        fmin = self.features.min()
        fmax = self.features.max()
        rng = fmax - fmin
        if rng > 0:
            self.features = (self.features - fmin) / rng * (hi - lo) + lo

    def normalize_zero_mean_unit_variance(self) -> None:
        mean = self.features.mean(axis=0, keepdims=True)
        std = self.features.std(axis=0, keepdims=True)
        self.features = (self.features - mean) / np.where(std > 0, std, 1.0)

    def as_tuple(self) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
        return self.features, self.labels, self.features_mask

    def __repr__(self) -> str:
        return (f"DataSet(features={self.features.shape}, "
                f"labels={self.labels.shape})")


class MultiDataSet:
    """Multiple named-position inputs/outputs for ComputationGraph training
    (parity: ND4J ``MultiDataSet`` — lists of feature/label arrays + masks,
    the currency of ``RecordReaderMultiDataSetIterator``)."""

    def __init__(self, features: List, labels: List,
                 features_masks: Optional[List] = None,
                 labels_masks: Optional[List] = None):
        self.features = [_as_array(f) for f in features]
        self.labels = [_as_array(l) for l in labels]
        self.features_masks = (None if features_masks is None
                               else [_as_array(m) for m in features_masks])
        self.labels_masks = (None if labels_masks is None
                             else [_as_array(m) for m in labels_masks])

    def num_examples(self) -> int:
        return self.features[0].shape[0]

    def num_inputs(self) -> int:
        return len(self.features)

    def num_outputs(self) -> int:
        return len(self.labels)

    @staticmethod
    def merge(datasets: List["MultiDataSet"]) -> "MultiDataSet":
        n_in = datasets[0].num_inputs()
        n_out = datasets[0].num_outputs()
        feats = [np.concatenate([d.features[i] for d in datasets], axis=0)
                 for i in range(n_in)]
        labels = [np.concatenate([d.labels[i] for d in datasets], axis=0)
                  for i in range(n_out)]
        fm = (None if datasets[0].features_masks is None else
              [np.concatenate([d.features_masks[i] for d in datasets], axis=0)
               for i in range(n_in)])
        lm = (None if datasets[0].labels_masks is None else
              [np.concatenate([d.labels_masks[i] for d in datasets], axis=0)
               for i in range(n_out)])
        return MultiDataSet(feats, labels, fm, lm)

    def __repr__(self) -> str:
        return (f"MultiDataSet(inputs={[f.shape for f in self.features]}, "
                f"outputs={[l.shape for l in self.labels]})")
