"""DataSetIterator framework + async device prefetch.

Parity: reference ``deeplearning4j-nn/.../datasets/iterator/`` —
``DataSetIterator`` contract, ``ListDataSetIterator``, ``ExistingDataSetIterator``,
``MultipleEpochsIterator``, ``SamplingDataSetIterator``, and
``AsyncDataSetIterator.java:36`` (background ``IteratorRunnable`` thread +
``LinkedBlockingQueue``).

TPU-native: ``AsyncDataSetIterator`` additionally issues ``jax.device_put`` on
the background thread so host→HBM DMA overlaps the previous step's compute —
the role the reference's device-affinity prefetch played for GPUs.

Seekable cursor protocol (``util.durable``): every in-tree iterator also
implements ``state() -> dict`` / ``restore(state)`` — a JSON-serializable
cursor such that restoring it on a freshly built pipeline reproduces the
remaining batch stream exactly (replays zero batches, skips none). The
async wrapper tags each prefetched batch with the base cursor captured
right after producing it, so ``state()`` always reflects what the
CONSUMER has seen, never the producer's read-ahead.
"""

from __future__ import annotations

import threading
from typing import Iterable, Iterator, List, Optional

import numpy as np

from .dataset import DataSet


class DataSetIterator:
    """Iterator contract (parity: ND4J ``DataSetIterator``).

    Subclasses implement ``next()`` / ``has_next()`` / ``reset()``.
    Iterating with ``for`` restarts from the current cursor; call ``reset()``
    for a fresh epoch (``MultiLayerNetwork.fit`` resets between epochs).
    """

    def next(self) -> DataSet:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    # Seekable cursor protocol (optional — ``util.durable.is_seekable``
    # probes for the METHODS, so subclasses without a cursor simply don't
    # define them): ``state() -> dict`` returns a JSON-serializable
    # cursor; ``restore(state)`` on an equivalently built iterator
    # reproduces the remaining batch stream exactly.

    @property
    def batch_size(self) -> int:
        raise NotImplementedError

    def __iter__(self) -> Iterator[DataSet]:
        while self.has_next():
            yield self.next()


class ArrayDataSetIterator(DataSetIterator):
    """Batches over in-memory arrays (parity: ``INDArrayDataSetIterator``)."""

    def __init__(self, features, labels, batch_size: int,
                 features_mask=None, labels_mask=None):
        self._data = DataSet(features, labels, features_mask, labels_mask)
        self._batch = int(batch_size)
        self._cursor = 0

    @property
    def batch_size(self) -> int:
        return self._batch

    def total_examples(self) -> int:
        return self._data.num_examples()

    def has_next(self) -> bool:
        return self._cursor < self._data.num_examples()

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        end = min(self._cursor + self._batch, self._data.num_examples())
        out = self._data._take(slice(self._cursor, end))
        self._cursor = end
        return out

    def reset(self) -> None:
        self._cursor = 0

    def state(self) -> dict:
        # the cursor indexes the CURRENT example order; a caller that
        # shuffles per epoch must re-apply the same seeded shuffle before
        # restore() for the stream to reproduce
        return {"cursor": int(self._cursor)}

    def restore(self, state: dict) -> None:
        self._cursor = int(state["cursor"])

    def shuffle(self, seed: Optional[int] = None) -> None:
        self._data.shuffle(seed)
        self._cursor = 0


class ListDataSetIterator(DataSetIterator):
    """Iterator over a pre-batched list (parity: ``ListDataSetIterator``)."""

    def __init__(self, datasets: Iterable[DataSet], batch_size: Optional[int] = None):
        self._list: List[DataSet] = list(datasets)
        self._batch = batch_size or (self._list[0].num_examples() if self._list else 0)
        self._cursor = 0

    @property
    def batch_size(self) -> int:
        return self._batch

    def has_next(self) -> bool:
        return self._cursor < len(self._list)

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        out = self._list[self._cursor]
        self._cursor += 1
        return out

    def reset(self) -> None:
        self._cursor = 0

    def state(self) -> dict:
        return {"cursor": int(self._cursor)}

    def restore(self, state: dict) -> None:
        self._cursor = int(state["cursor"])


class ExistingDataSetIterator(DataSetIterator):
    """Wrap a plain python iterable of DataSets (parity:
    ``ExistingDataSetIterator``). Resettable only if the source is re-iterable."""

    def __init__(self, source: Iterable[DataSet]):
        self._source = source
        self._iter = iter(source)
        self._peek: Optional[DataSet] = None

    @property
    def batch_size(self) -> int:
        return -1

    def has_next(self) -> bool:
        if self._peek is None:
            try:
                self._peek = next(self._iter)
            except StopIteration:
                return False
        return True

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        out, self._peek = self._peek, None
        return out

    def reset(self) -> None:
        self._iter = iter(self._source)
        self._peek = None


class MultipleEpochsIterator(DataSetIterator):
    """Repeat an underlying iterator N times (parity: ``MultipleEpochsIterator``)."""

    def __init__(self, epochs: int, base: DataSetIterator):
        self.epochs = int(epochs)
        self.base = base
        self._epoch = 0

    @property
    def batch_size(self) -> int:
        return self.base.batch_size

    def has_next(self) -> bool:
        if self.base.has_next():
            return True
        if self._epoch + 1 < self.epochs:
            self._epoch += 1
            self.base.reset()
            return self.base.has_next()
        return False

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.base.next()

    def reset(self) -> None:
        self._epoch = 0
        self.base.reset()

    def seekable(self) -> bool:
        """Only as seekable as the base — state() delegates to it."""
        from ..util.durable import is_seekable
        return is_seekable(self.base)

    def state(self) -> dict:
        return {"epoch": int(self._epoch), "base": self.base.state()}

    def restore(self, state: dict) -> None:
        self._epoch = int(state["epoch"])
        self.base.restore(state["base"])


class SamplingDataSetIterator(DataSetIterator):
    """Random with-replacement samples from one DataSet (parity:
    ``SamplingDataSetIterator``)."""

    def __init__(self, data: DataSet, batch_size: int, total_batches: int,
                 seed: Optional[int] = None):
        self._data = data
        self._batch = int(batch_size)
        self._total = int(total_batches)
        self._seed = seed
        self._rng = np.random.default_rng(seed)
        self._count = 0

    @property
    def batch_size(self) -> int:
        return self._batch

    def has_next(self) -> bool:
        return self._count < self._total

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        self._count += 1
        idx = self._rng.choice(self._data.num_examples(), size=self._batch,
                               replace=True)
        return self._data._take(idx)

    def reset(self) -> None:
        self._count = 0
        self._rng = np.random.default_rng(self._seed)

    def state(self) -> dict:
        # bit_generator.state is a JSON-friendly dict (ints + strings), so
        # restore reproduces the EXACT sample stream, not just the count
        return {"count": int(self._count),
                "rng": self._rng.bit_generator.state}

    def restore(self, state: dict) -> None:
        self._count = int(state["count"])
        self._rng = np.random.default_rng()
        self._rng.bit_generator.state = state["rng"]


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch + optional device placement.

    Parity: ``AsyncDataSetIterator.java:36`` — a producer thread drains the
    base iterator into a bounded queue while the training loop consumes.
    With ``device_put=True`` the producer also ships each batch to the
    device so the next step's HBM transfer overlaps the current step.

    The producer watches a stop flag between puts, so ``reset()`` is
    O(queue_size): it poisons the running producer, discards the staged
    queue, and restarts on a reset base — it does NOT drain the rest of
    the epoch through the consumer. Producer errors are raised on the
    consumer as soon as they are observed (fail fast), not deferred until
    every already-staged batch has been drained.

    Seekable: when the base iterator is, the producer tags every queued
    batch with ``base.state()`` captured right after producing it, and the
    consumer records the tag as each batch is handed out — so ``state()``
    is always the cursor of the last CONSUMED batch (prefetched-but-unread
    batches are replayed after a ``restore()``, never skipped).
    """

    def __init__(self, base: DataSetIterator, queue_size: int = 2,
                 device_put: bool = False, device=None):
        self.base = base
        self.queue_size = max(1, int(queue_size))
        self.device_put = device_put
        self.device = device
        self._peek = None
        self._start()

    def _stage(self, ds):
        import jax
        return DataSet(
            jax.device_put(ds.features, self.device),
            jax.device_put(ds.labels, self.device),
            None if ds.features_mask is None
            else jax.device_put(ds.features_mask, self.device),
            None if ds.labels_mask is None
            else jax.device_put(ds.labels_mask, self.device))

    def _producer(self, pq) -> None:
        try:
            seekable = self._base_seekable
            for ds in self.base:
                if pq.stop.is_set():
                    return
                # post-read cursor of THIS batch (the base's __iter__
                # advances exactly one item per yield)
                cursor = self.base.state() if seekable else None
                if self.device_put:
                    ds = self._stage(ds)
                if not pq.put((ds, cursor)):
                    return
        except BaseException as e:  # surfaced on the consumer side
            pq.fail(e)
        finally:
            pq.finish()

    def _start(self) -> None:
        from ..util.ingest import ProducerQueue
        # the shared probe (both protocol halves + the base's own veto):
        # a base with state() but no restore() must NOT be reported
        # seekable — the failure would otherwise surface as an
        # AttributeError at resume time
        from ..util.durable import is_seekable
        self._base_seekable = is_seekable(self.base)
        # cursor of "nothing consumed yet" — captured BEFORE the producer
        # thread starts racing ahead on the base
        self._cursor = self.base.state() if self._base_seekable else None
        self._pq = ProducerQueue(self.queue_size)
        self._thread = threading.Thread(
            target=self._producer, args=(self._pq,), daemon=True)
        self._thread.start()

    @property
    def batch_size(self) -> int:
        return self.base.batch_size

    def has_next(self) -> bool:
        if self._peek is None:
            try:
                # fail fast: a producer error raises here as soon as it
                # is observed, even with staged batches still queued
                self._peek = self._pq.get()
            except BaseException:
                self._peek = self._pq.SENTINEL   # stream over after error
                raise
        return self._peek is not self._pq.SENTINEL

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        (out, cursor), self._peek = self._peek, None
        if cursor is not None:
            self._cursor = cursor
        return out

    def reset(self) -> None:
        if not self._pq.drain_and_join(self._thread):
            # restarting would race a second producer against the same
            # base iterator — refuse instead of corrupting it
            raise RuntimeError(
                "async producer did not stop within 5s (base iterator "
                "blocked in next()?) — cannot safely reset")
        self._peek = None
        self.base.reset()
        self._start()

    def seekable(self) -> bool:
        """The wrapper is only as seekable as its base
        (``util.durable.is_seekable`` probes this)."""
        return self._base_seekable

    def state(self) -> dict:
        """Cursor of the last batch the CONSUMER took (prefetched batches
        still in the queue are not consumed and will be replayed)."""
        if not self._base_seekable:
            raise NotImplementedError(
                f"base {type(self.base).__name__} has no seekable cursor")
        return self._cursor

    def restore(self, state: dict) -> None:
        if not self._pq.drain_and_join(self._thread):
            raise RuntimeError(
                "async producer did not stop within 5s (base iterator "
                "blocked in next()?) — cannot safely restore")
        self._peek = None
        self.base.restore(state)
        self._start()

    def close(self) -> None:
        """Stop the producer without restarting (for abandoned epochs).
        Best effort: nothing restarts over the base, so a stuck producer
        is left to die with the process."""
        self._pq.drain_and_join(self._thread)
        self._peek = self._pq.SENTINEL


class AsyncMultiDataSetIterator(AsyncDataSetIterator):
    """Background prefetch for MultiDataSet iterators — the multi-input/
    multi-output ComputationGraph feed (parity:
    ``AsyncMultiDataSetIterator.java``). Same producer/queue machinery as
    :class:`AsyncDataSetIterator`; only the device staging differs."""

    def _stage(self, mds):
        import jax
        from .dataset import MultiDataSet
        put = lambda xs: (None if xs is None
                          else [jax.device_put(x, self.device) for x in xs])
        return MultiDataSet(put(mds.features), put(mds.labels),
                            put(mds.features_masks), put(mds.labels_masks))
