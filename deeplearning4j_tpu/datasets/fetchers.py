"""Canned datasets: MNIST and Iris.

Parity: reference ``deeplearning4j-core/.../datasets/fetchers/MnistDataFetcher.java``
(+ ``base/MnistFetcher.java:43-51`` download/cache, ``mnist/MnistManager.java``
idx readers) and ``IrisDataFetcher.java``; iterators
``MnistDataSetIterator.java`` / ``IrisDataSetIterator.java``.

Offline behavior: this environment has zero egress, so instead of the
reference's HTTP download we (1) read standard idx-format files from a local
cache directory if present (``$DL4J_TPU_DATA_DIR``, ``~/.cache/mnist``,
``~/.deeplearning4j/MNIST``), and (2) otherwise synthesize a deterministic
MNIST-surrogate: 28×28 images with class-dependent geometric structure plus
noise — learnable to >97% by LeNet, so the end-to-end milestone is exercised
with identical shapes/dtypes to real MNIST. The surrogate is clearly flagged
via ``MnistDataSetIterator.synthetic``.
"""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from .dataset import DataSet
from .iterator import ArrayDataSetIterator

# ----------------------------------------------------------------------
# idx-file parsing (the real MNIST binary format, MnistManager analog)
# ----------------------------------------------------------------------


def read_idx(path: str) -> np.ndarray:
    """Parse an idx-format file (optionally gzipped)."""
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0:
            raise ValueError(f"{path}: not an idx file (magic={zero})")
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dtype = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
                 0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}[dtype_code]
        data = np.frombuffer(f.read(), dtype=np.dtype(dtype).newbyteorder(">"))
        return data.reshape(dims)


_MNIST_FILES = {
    "train_images": ("train-images-idx3-ubyte", "train-images-idx3-ubyte.gz"),
    "train_labels": ("train-labels-idx1-ubyte", "train-labels-idx1-ubyte.gz"),
    "test_images": ("t10k-images-idx3-ubyte", "t10k-images-idx3-ubyte.gz"),
    "test_labels": ("t10k-labels-idx1-ubyte", "t10k-labels-idx1-ubyte.gz"),
}


def _mnist_dirs():
    env = os.environ.get("DL4J_TPU_DATA_DIR")
    cands = []
    if env:
        cands.append(Path(env) / "mnist")
        cands.append(Path(env))
    cands.append(Path.home() / ".cache" / "mnist")
    cands.append(Path.home() / ".deeplearning4j" / "MNIST")
    return cands


def _mnist_file(d: Path, key: str) -> Optional[Path]:
    for cand in _MNIST_FILES[key]:
        if (d / cand).exists():
            return d / cand
    return None


def _find_mnist(train: bool) -> Optional[Path]:
    """Directory holding BOTH the image and label file for the requested
    split, else None (→ synthetic fallback)."""
    img_key = "train_images" if train else "test_images"
    lbl_key = "train_labels" if train else "test_labels"
    for d in _mnist_dirs():
        if not d.is_dir():
            continue
        if _mnist_file(d, img_key) and _mnist_file(d, lbl_key):
            return d
    return None


def _load_real_mnist(d: Path, train: bool) -> Tuple[np.ndarray, np.ndarray]:
    img_key = "train_images" if train else "test_images"
    lbl_key = "train_labels" if train else "test_labels"
    images = read_idx(str(_mnist_file(d, img_key))).astype(np.float32) / 255.0
    labels = read_idx(str(_mnist_file(d, lbl_key))).astype(np.int64)
    return images.reshape(len(images), -1), labels


# ----------------------------------------------------------------------
# deterministic synthetic MNIST surrogate
# ----------------------------------------------------------------------


def _synthetic_mnist(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """28×28 grayscale images whose class determines geometric structure:
    each digit d gets a distinct combination of a horizontal bar, vertical
    bar, and filled disc whose positions derive from d. Learnable by a
    convnet but not linearly trivial (noise + jitter)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    imgs = np.zeros((n, 28, 28), dtype=np.float32)
    yy, xx = np.mgrid[0:28, 0:28]
    for i in range(n):
        d = int(labels[i])
        jx, jy = rng.integers(-2, 3, size=2)
        # horizontal bar at row 4 + 2*d (mod 24), vertical bar mirrored
        r = (4 + 2 * d) % 24 + jy
        c = (24 - 2 * d) % 24 + jx
        img = np.zeros((28, 28), dtype=np.float32)
        img[np.clip(r, 0, 27):np.clip(r + 3, 0, 28), 4:24] = 0.8
        img[4:24, np.clip(c, 0, 27):np.clip(c + 3, 0, 28)] = 0.8
        # disc whose center angle encodes d
        ang = 2 * np.pi * d / 10.0
        cy, cx = 14 + 8 * np.sin(ang) + jy, 14 + 8 * np.cos(ang) + jx
        disc = ((yy - cy) ** 2 + (xx - cx) ** 2) < (3 + (d % 3)) ** 2
        img[disc] = 1.0
        imgs[i] = img
    imgs += rng.normal(0.0, 0.08, size=imgs.shape).astype(np.float32)
    imgs = np.clip(imgs, 0.0, 1.0)
    return imgs.reshape(n, 784), labels


def _one_hot(labels: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((len(labels), n), dtype=np.float32)
    out[np.arange(len(labels)), labels] = 1.0
    return out


class MnistDataSetIterator(ArrayDataSetIterator):
    """MNIST batches: features [b, 784] in [0,1], labels one-hot [b, 10].

    Parity: ``MnistDataSetIterator(batch, numExamples, binarize, train,
    shuffle, seed)``.
    """

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 binarize: bool = False, train: bool = True,
                 shuffle: bool = True, seed: int = 123):
        d = _find_mnist(train)
        self.synthetic = d is None
        if d is not None:
            feats, labels = _load_real_mnist(d, train)
        else:
            total = num_examples or (60000 if train else 10000)
            # train/test draw from disjoint seed streams
            feats, labels = _synthetic_mnist(total, seed + (0 if train else 10_000_019))
        if num_examples is not None:
            feats, labels = feats[:num_examples], labels[:num_examples]
        if binarize:
            feats = (feats > 0.5).astype(np.float32)
        if shuffle:
            order = np.random.default_rng(seed).permutation(len(feats))
            feats, labels = feats[order], labels[order]
        super().__init__(feats.astype(np.float32), _one_hot(labels, 10), batch_size)


class IrisDataSetIterator(ArrayDataSetIterator):
    """Iris-shaped 3-class dataset: features [b, 4], labels one-hot [b, 3].

    Parity: ``IrisDataFetcher.java`` / ``IrisDataSetIterator.java``. Offline
    surrogate: three 4-D Gaussian clusters with means/covariances matching the
    published per-class statistics of Fisher's iris data (setosa/versicolor/
    virginica sepal+petal length/width), deterministic by seed — same shapes,
    same learnability profile.
    """

    # per-class feature means (sepal_l, sepal_w, petal_l, petal_w) and stds —
    # the published summary statistics of the classic dataset
    _MEANS = np.array([[5.006, 3.428, 1.462, 0.246],
                       [5.936, 2.770, 4.260, 1.326],
                       [6.588, 2.974, 5.552, 2.026]])
    _STDS = np.array([[0.352, 0.379, 0.174, 0.105],
                      [0.516, 0.314, 0.470, 0.198],
                      [0.636, 0.322, 0.552, 0.275]])

    def __init__(self, batch_size: int = 150, num_examples: int = 150,
                 seed: int = 6):
        rng = np.random.default_rng(seed)
        per = num_examples // 3
        feats, labels = [], []
        for c in range(3):
            n = per if c < 2 else num_examples - 2 * per
            feats.append(rng.normal(self._MEANS[c], self._STDS[c], size=(n, 4)))
            labels.append(np.full(n, c))
        feats = np.concatenate(feats).astype(np.float32)
        labels = np.concatenate(labels)
        order = rng.permutation(len(feats))
        super().__init__(feats[order], _one_hot(labels[order], 3), batch_size)
