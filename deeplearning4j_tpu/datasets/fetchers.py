"""Canned datasets: MNIST and Iris.

Parity: reference ``deeplearning4j-core/.../datasets/fetchers/MnistDataFetcher.java``
(+ ``base/MnistFetcher.java:43-51`` download/cache, ``mnist/MnistManager.java``
idx readers) and ``IrisDataFetcher.java``; iterators
``MnistDataSetIterator.java`` / ``IrisDataSetIterator.java``.

Offline behavior: this environment has zero egress, so instead of the
reference's HTTP download we (1) read standard idx-format files from a local
cache directory if present (``$DL4J_TPU_DATA_DIR``, ``~/.cache/mnist``,
``~/.deeplearning4j/MNIST``), and (2) otherwise synthesize a deterministic
MNIST-surrogate: 28×28 images with class-dependent geometric structure plus
noise — learnable to >97% by LeNet, so the end-to-end milestone is exercised
with identical shapes/dtypes to real MNIST. The surrogate is clearly flagged
via ``MnistDataSetIterator.synthetic``.
"""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from .dataset import DataSet
from .iterator import ArrayDataSetIterator

# ----------------------------------------------------------------------
# idx-file parsing (the real MNIST binary format, MnistManager analog)
# ----------------------------------------------------------------------


def read_idx(path: str) -> np.ndarray:
    """Parse an idx-format file (optionally gzipped)."""
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0:
            raise ValueError(f"{path}: not an idx file (magic={zero})")
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dtype = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
                 0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}[dtype_code]
        data = np.frombuffer(f.read(), dtype=np.dtype(dtype).newbyteorder(">"))
        return data.reshape(dims)


_MNIST_FILES = {
    "train_images": ("train-images-idx3-ubyte", "train-images-idx3-ubyte.gz"),
    "train_labels": ("train-labels-idx1-ubyte", "train-labels-idx1-ubyte.gz"),
    "test_images": ("t10k-images-idx3-ubyte", "t10k-images-idx3-ubyte.gz"),
    "test_labels": ("t10k-labels-idx1-ubyte", "t10k-labels-idx1-ubyte.gz"),
}


def _cache_dirs(*names: str):
    """Candidate cache dirs for a dataset: ``$DL4J_TPU_DATA_DIR/<name>``
    (plus the root itself), ``~/.cache/<name>``, ``~/.deeplearning4j/<name>``."""
    env = os.environ.get("DL4J_TPU_DATA_DIR")
    cands = []
    if env:
        for n in names:
            cands.append(Path(env) / n)
        cands.append(Path(env))
    for n in names:
        cands.append(Path.home() / ".cache" / n)
        cands.append(Path.home() / ".deeplearning4j" / n)
    return cands


def _mnist_dirs():
    return _cache_dirs("mnist", "MNIST")


def _mnist_file(d: Path, key: str) -> Optional[Path]:
    for cand in _MNIST_FILES[key]:
        if (d / cand).exists():
            return d / cand
    return None


def _find_mnist(train: bool) -> Optional[Path]:
    """Directory holding BOTH the image and label file for the requested
    split; attempts an HTTP download into the cache when absent (parity:
    ``MnistFetcher.java:43`` lazy download); None → synthetic fallback."""
    img_key = "train_images" if train else "test_images"
    lbl_key = "train_labels" if train else "test_labels"
    for d in _mnist_dirs():
        if not d.is_dir():
            continue
        if _mnist_file(d, img_key) and _mnist_file(d, lbl_key):
            return d
    from .downloader import auto_download_enabled, fetch_mnist
    if auto_download_enabled():
        d = fetch_mnist()
        if d is not None and _mnist_file(d, img_key) and _mnist_file(d, lbl_key):
            return d
    return None


def _load_real_mnist(d: Path, train: bool) -> Tuple[np.ndarray, np.ndarray]:
    img_key = "train_images" if train else "test_images"
    lbl_key = "train_labels" if train else "test_labels"
    images = read_idx(str(_mnist_file(d, img_key))).astype(np.float32) / 255.0
    labels = read_idx(str(_mnist_file(d, lbl_key))).astype(np.int64)
    return images.reshape(len(images), -1), labels


# ----------------------------------------------------------------------
# deterministic synthetic MNIST surrogate
# ----------------------------------------------------------------------


def _synthetic_mnist(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """28×28 grayscale images whose class determines geometric structure:
    each digit d gets a distinct combination of a horizontal bar, vertical
    bar, and filled disc whose positions derive from d. Learnable by a
    convnet but not linearly trivial (noise + jitter)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    imgs = np.zeros((n, 28, 28), dtype=np.float32)
    yy, xx = np.mgrid[0:28, 0:28]
    for i in range(n):
        d = int(labels[i])
        jx, jy = rng.integers(-2, 3, size=2)
        # horizontal bar at row 4 + 2*d (mod 24), vertical bar mirrored
        r = (4 + 2 * d) % 24 + jy
        c = (24 - 2 * d) % 24 + jx
        img = np.zeros((28, 28), dtype=np.float32)
        img[np.clip(r, 0, 27):np.clip(r + 3, 0, 28), 4:24] = 0.8
        img[4:24, np.clip(c, 0, 27):np.clip(c + 3, 0, 28)] = 0.8
        # disc whose center angle encodes d
        ang = 2 * np.pi * d / 10.0
        cy, cx = 14 + 8 * np.sin(ang) + jy, 14 + 8 * np.cos(ang) + jx
        disc = ((yy - cy) ** 2 + (xx - cx) ** 2) < (3 + (d % 3)) ** 2
        img[disc] = 1.0
        imgs[i] = img
    imgs += rng.normal(0.0, 0.08, size=imgs.shape).astype(np.float32)
    imgs = np.clip(imgs, 0.0, 1.0)
    return imgs.reshape(n, 784), labels


def _one_hot(labels: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((len(labels), n), dtype=np.float32)
    out[np.arange(len(labels)), labels] = 1.0
    return out


class MnistDataSetIterator(ArrayDataSetIterator):
    """MNIST batches: features [b, 784] in [0,1], labels one-hot [b, 10].

    Parity: ``MnistDataSetIterator(batch, numExamples, binarize, train,
    shuffle, seed)``.
    """

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 binarize: bool = False, train: bool = True,
                 shuffle: bool = True, seed: int = 123):
        d = _find_mnist(train)
        self.synthetic = d is None
        if d is not None:
            feats, labels = _load_real_mnist(d, train)
        else:
            total = num_examples or (60000 if train else 10000)
            # train/test draw from disjoint seed streams
            feats, labels = _synthetic_mnist(total, seed + (0 if train else 10_000_019))
        if num_examples is not None:
            feats, labels = feats[:num_examples], labels[:num_examples]
        if binarize:
            feats = (feats > 0.5).astype(np.float32)
        if shuffle:
            order = np.random.default_rng(seed).permutation(len(feats))
            feats, labels = feats[order], labels[order]
        super().__init__(feats.astype(np.float32), _one_hot(labels, 10), batch_size)


# ----------------------------------------------------------------------
# CIFAR-10 (binary-batch format) — CifarDataSetIterator.java:1-175 analog
# ----------------------------------------------------------------------

_CIFAR_TRAIN = [f"data_batch_{i}.bin" for i in range(1, 6)]
_CIFAR_TEST = ["test_batch.bin"]
_CIFAR_RECORD = 1 + 3 * 32 * 32  # label byte + CHW uint8 pixels


def _cifar_dirs():
    base = _cache_dirs("cifar10", "cifar-10-batches-bin", "cifar")
    # fetch_cifar10 extracts to <cache>/cifar-10-batches-bin — scan those
    # nested layouts too so cached downloads are found even with
    # DL4J_TPU_AUTO_DOWNLOAD=0 (code review r4)
    return base + [d / "cifar-10-batches-bin" for d in base]


def _find_cifar(train: bool) -> Optional[Path]:
    names = _CIFAR_TRAIN if train else _CIFAR_TEST
    for d in _cifar_dirs():
        if d.is_dir() and all((d / n).exists() for n in names):
            return d
    from .downloader import auto_download_enabled, fetch_cifar10
    if auto_download_enabled():
        d = fetch_cifar10()
        if d is not None and all((d / n).exists() for n in names):
            return d
    return None


def read_cifar_bin(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Parse one CIFAR-10 binary batch: each record is a label byte followed
    by 3072 CHW uint8 pixels. Returns (images [n, 32, 32, 3] float32 in
    [0,1] NHWC — the TPU-friendly layout — and labels [n])."""
    raw = np.fromfile(path, dtype=np.uint8)
    if raw.size % _CIFAR_RECORD != 0:
        raise ValueError(f"{path}: size {raw.size} not a multiple of "
                         f"{_CIFAR_RECORD}-byte CIFAR records")
    recs = raw.reshape(-1, _CIFAR_RECORD)
    labels = recs[:, 0].astype(np.int64)
    imgs = recs[:, 1:].reshape(-1, 3, 32, 32).astype(np.float32) / 255.0
    return imgs.transpose(0, 2, 3, 1), labels


def _synthetic_cifar(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """32×32 RGB surrogate: class determines a dominant hue gradient plus a
    textured patch, so a convnet can learn it but pixels aren't trivially
    separable."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    yy, xx = np.mgrid[0:32, 0:32] / 31.0
    imgs = np.zeros((n, 32, 32, 3), dtype=np.float32)
    for i in range(n):
        d = int(labels[i])
        ang = 2 * np.pi * d / 10.0
        base = 0.5 + 0.4 * np.cos(ang) * xx + 0.4 * np.sin(ang) * yy
        img = np.stack([base * (0.4 + 0.06 * ((d + k) % 3))
                        for k in range(3)], axis=-1)
        cy, cx = rng.integers(8, 24, size=2)
        img[cy - 4:cy + 4, cx - 4:cx + 4, d % 3] += 0.45
        imgs[i] = img
    imgs += rng.normal(0.0, 0.05, size=imgs.shape).astype(np.float32)
    return np.clip(imgs, 0.0, 1.0), labels


class CifarDataSetIterator(ArrayDataSetIterator):
    """CIFAR-10 batches (parity: ``CifarDataSetIterator.java:1-175``).

    Features ``[b, 32, 32, 3]`` NHWC float32 in [0,1] (the reference emits
    CHW; NHWC keeps channels minor for XLA conv layouts), labels one-hot
    ``[b, 10]``. Reads the standard binary-batch files from a local cache
    dir; deterministic synthetic surrogate otherwise (``synthetic`` flag).
    """

    NUM_CLASSES = 10
    LABELS = ["airplane", "automobile", "bird", "cat", "deer",
              "dog", "frog", "horse", "ship", "truck"]

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 train: bool = True, shuffle: bool = True, seed: int = 123,
                 flatten: bool = False):
        d = _find_cifar(train)
        self.synthetic = d is None
        if d is not None:
            names = _CIFAR_TRAIN if train else _CIFAR_TEST
            parts = [read_cifar_bin(str(d / n)) for n in names]
            feats = np.concatenate([p[0] for p in parts])
            labels = np.concatenate([p[1] for p in parts])
        else:
            total = num_examples or (50000 if train else 10000)
            feats, labels = _synthetic_cifar(
                total, seed + (0 if train else 10_000_019))
        # shuffle BEFORE truncating: a subset must sample across the whole
        # training set, not a deterministic prefix of data_batch_1 (ADVICE r3)
        if shuffle:
            order = np.random.default_rng(seed).permutation(len(feats))
            feats, labels = feats[order], labels[order]
        if num_examples is not None:
            feats, labels = feats[:num_examples], labels[:num_examples]
        if flatten:
            feats = feats.reshape(len(feats), -1)
        super().__init__(feats, _one_hot(labels, 10), batch_size)


# ----------------------------------------------------------------------
# LFW (labeled faces) — LFWDataSetIterator analog
# ----------------------------------------------------------------------


def _lfw_dirs():
    return _cache_dirs("lfw")


def _find_lfw() -> Optional[Path]:
    """A directory is an LFW cache only if it actually holds the standard
    ``<person>/*.jpg`` layout (a root cached for another dataset must fall
    through to the synthetic surrogate, not crash the loader)."""
    for d in _lfw_dirs():
        if not d.is_dir():
            continue
        if any(p.is_dir() and any(p.glob("*.jpg")) for p in d.iterdir()):
            return d
    return None


class LFWDataSetIterator(ArrayDataSetIterator):
    """Labeled-faces batches (parity: ``LFWDataSetIterator.java``).

    Scans ``<cache>/lfw/<person>/*.jpg`` directories (the standard LFW
    layout), decodes + resizes via PIL, labels = person identity one-hot
    over the ``num_labels`` most-photographed people. Synthetic face-like
    surrogate (``synthetic`` flag) when the dataset is absent.
    """

    def __init__(self, batch_size: int, num_examples: int = 1000,
                 num_labels: int = 10, image_shape: Tuple[int, int] = (64, 64),
                 shuffle: bool = True, seed: int = 123):
        d = _find_lfw()
        self.synthetic = d is None
        h, w = image_shape
        if d is not None:
            from PIL import Image
            people = sorted((p for p in d.iterdir()
                             if p.is_dir() and any(p.glob("*.jpg"))),
                            key=lambda p: -len(list(p.glob("*.jpg"))))
            people = people[:num_labels]
            self.labels_list = [p.name for p in people]
            feats, labels = [], []
            for ci, person in enumerate(people):
                for img_path in sorted(person.glob("*.jpg")):
                    if len(feats) >= num_examples:
                        break
                    img = Image.open(img_path).convert("RGB").resize((w, h))
                    feats.append(np.asarray(img, dtype=np.float32) / 255.0)
                    labels.append(ci)
            feats = np.stack(feats)
            labels = np.asarray(labels)
        else:
            rng = np.random.default_rng(seed)
            labels = rng.integers(0, num_labels, size=num_examples)
            yy, xx = np.mgrid[0:h, 0:w]
            feats = np.zeros((num_examples, h, w, 3), dtype=np.float32)
            self.labels_list = [f"person_{i}" for i in range(num_labels)]
            for i in range(num_examples):
                c = int(labels[i])
                # face-ish blob whose geometry depends on identity
                cy, cx = h * (0.35 + 0.03 * (c % 5)), w * (0.5 + 0.02 * (c % 3))
                r2 = ((yy - cy) / (0.30 * h)) ** 2 + ((xx - cx) / (0.22 * w)) ** 2
                face = np.clip(1.2 - r2, 0, 1)
                tone = 0.35 + 0.05 * (c % 7)
                img = np.stack([face * (tone + 0.08 * k) for k in range(3)],
                               axis=-1)
                feats[i] = np.clip(
                    img + rng.normal(0, 0.04, size=img.shape), 0, 1)
        if shuffle:
            order = np.random.default_rng(seed).permutation(len(feats))
            feats, labels = feats[order], labels[order]
        super().__init__(feats, _one_hot(labels, num_labels), batch_size)


class IrisDataSetIterator(ArrayDataSetIterator):
    """Iris-shaped 3-class dataset: features [b, 4], labels one-hot [b, 3].

    Parity: ``IrisDataFetcher.java`` / ``IrisDataSetIterator.java``. Offline
    surrogate: three 4-D Gaussian clusters with means/covariances matching the
    published per-class statistics of Fisher's iris data (setosa/versicolor/
    virginica sepal+petal length/width), deterministic by seed — same shapes,
    same learnability profile.
    """

    # per-class feature means (sepal_l, sepal_w, petal_l, petal_w) and stds —
    # the published summary statistics of the classic dataset
    _MEANS = np.array([[5.006, 3.428, 1.462, 0.246],
                       [5.936, 2.770, 4.260, 1.326],
                       [6.588, 2.974, 5.552, 2.026]])
    _STDS = np.array([[0.352, 0.379, 0.174, 0.105],
                      [0.516, 0.314, 0.470, 0.198],
                      [0.636, 0.322, 0.552, 0.275]])

    def __init__(self, batch_size: int = 150, num_examples: int = 150,
                 seed: int = 6):
        rng = np.random.default_rng(seed)
        per = num_examples // 3
        feats, labels = [], []
        for c in range(3):
            n = per if c < 2 else num_examples - 2 * per
            feats.append(rng.normal(self._MEANS[c], self._STDS[c], size=(n, 4)))
            labels.append(np.full(n, c))
        feats = np.concatenate(feats).astype(np.float32)
        labels = np.concatenate(labels)
        order = rng.permutation(len(feats))
        super().__init__(feats[order], _one_hot(labels[order], 3), batch_size)


# ----------------------------------------------------------------------
# Curves — CurvesDataFetcher analog
# ----------------------------------------------------------------------


def _synthetic_curves(n: int, seed: int, size: int = 28) -> np.ndarray:
    """28×28 grayscale images of random cubic Bézier curves — the shape of
    Hinton's deep-autoencoder "curves" dataset.

    Parity: ``deeplearning4j-core/.../datasets/fetchers/CurvesDataFetcher.java``
    downloads a pre-serialized ND4J DataSet (a JVM binary this framework
    deliberately does not parse); this is a faithful generative surrogate —
    each example is a smooth random curve rasterized with anti-aliasing,
    matching the original's construction (random control points → curve
    image) and its unsupervised use (features double as targets).
    """
    rng = np.random.default_rng(seed)
    # sample the Bézier densely and splat with bilinear weights
    t = np.linspace(0.0, 1.0, 160)
    b0 = (1 - t) ** 3
    b1 = 3 * t * (1 - t) ** 2
    b2 = 3 * t ** 2 * (1 - t)
    b3 = t ** 3
    imgs = np.zeros((n, size, size), dtype=np.float32)
    pts = rng.uniform(2.0, size - 3.0, size=(n, 4, 2))
    basis = np.stack([b0, b1, b2, b3])                  # [4, T]
    curves = np.einsum("kt,nkd->ntd", basis, pts)       # [n, T, 2]
    cx, cy = curves[..., 0], curves[..., 1]             # [n, T]
    x0, y0 = np.floor(cx).astype(int), np.floor(cy).astype(int)
    fx, fy = cx - x0, cy - y0
    idx = np.broadcast_to(np.arange(n)[:, None], cx.shape)
    np.add.at(imgs, (idx, y0, x0), (1 - fx) * (1 - fy))
    np.add.at(imgs, (idx, y0, x0 + 1), fx * (1 - fy))
    np.add.at(imgs, (idx, y0 + 1, x0), (1 - fx) * fy)
    np.add.at(imgs, (idx, y0 + 1, x0 + 1), fx * fy)
    return np.clip(imgs, 0.0, 1.0).reshape(n, size * size)


class CurvesDataSetIterator(ArrayDataSetIterator):
    """Curves dataset for unsupervised pretraining (labels == features, the
    autoencoder convention of the reference's fetcher).

    Parity: ``CurvesDataFetcher.java`` + ``datasets/iterator/impl`` usage in
    deep-autoencoder examples. A cached ``curves.npz`` (key ``data``,
    [n, 784] float) under the dataset cache dirs is used when present;
    otherwise the generative surrogate above.
    """

    def __init__(self, batch_size: int = 100, num_examples: int = 1000,
                 seed: int = 42):
        data = None
        for d in _cache_dirs("curves"):
            f = d / "curves.npz"
            if f.exists():
                data = np.load(f)["data"][:num_examples].astype(np.float32)
                break
        self.synthetic = data is None
        if data is None:
            data = _synthetic_curves(num_examples, seed)
        super().__init__(data, data.copy(), batch_size)
