"""Data pipeline: DataSet container, iterator framework, canned datasets.

Parity: reference ``deeplearning4j-core/.../datasets/`` (fetchers:
``MnistDataFetcher.java``, ``IrisDataFetcher.java``; iterators:
``MnistDataSetIterator.java``, ``IrisDataSetIterator.java``) and
``deeplearning4j-nn/.../datasets/iterator/`` (``AsyncDataSetIterator.java:36``,
``BaseDatasetIterator``, ``MultipleEpochsIterator``, ``SamplingDataSetIterator``,
``ListDataSetIterator``).

TPU-native design: iterators yield host numpy batches; ``AsyncDataSetIterator``
overlaps host-side batch assembly and host→device transfer with device compute
via a background thread + ``jax.device_put`` double-buffering — the analog of
the reference's prefetch thread with device affinity
(``AsyncDataSetIterator.java:75-76``).
"""

from .dataset import DataSet, MultiDataSet
from .iterator import (
    ArrayDataSetIterator,
    AsyncDataSetIterator,
    AsyncMultiDataSetIterator,
    DataSetIterator,
    ExistingDataSetIterator,
    ListDataSetIterator,
    MultipleEpochsIterator,
    SamplingDataSetIterator,
)
from .fetchers import (
    CifarDataSetIterator,
    IrisDataSetIterator,
    LFWDataSetIterator,
    MnistDataSetIterator,
)

__all__ = [
    "DataSet",
    "MultiDataSet",
    "DataSetIterator",
    "ArrayDataSetIterator",
    "ListDataSetIterator",
    "ExistingDataSetIterator",
    "MultipleEpochsIterator",
    "SamplingDataSetIterator",
    "AsyncDataSetIterator",
    "AsyncMultiDataSetIterator",
    "MnistDataSetIterator",
    "IrisDataSetIterator",
    "CifarDataSetIterator",
    "LFWDataSetIterator",
]
