"""Convolution / pooling / LRN lowerings (NHWC, TPU-native layout).

Replaces the reference's im2col path (``nn/layers/convolution/
ConvolutionLayer.java:251`` preOutput) and the CudnnConvolutionHelper /
CudnnSubsamplingHelper / CudnnLocalResponseNormalizationHelper bindings
(``deeplearning4j-cuda/.../CudnnConvolutionHelper.java:48``): on TPU a single
``lax.conv_general_dilated`` HLO is tiled onto the MXU by XLA, and elementwise
pre/post ops fuse into it — no descriptor/workspace management needed.

Layouts: activations NHWC ``[batch, h, w, channels]``, kernels HWIO
``[kh, kw, in_c, out_c]``.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import numpy as np
from jax import lax
import jax.numpy as jnp

Padding = Union[str, Tuple[int, int]]

DIMSPEC = ("NHWC", "HWIO", "NHWC")


def _pad_pairs(padding: Padding, kernel, stride, in_hw):
    if isinstance(padding, str):
        return padding.upper()  # "SAME" / "VALID" handled by lax
    ph, pw = padding
    return ((ph, ph), (pw, pw))


def conv2d(x, w, stride=(1, 1), padding: Padding = (0, 0), dilation=(1, 1),
           groups: int = 1, preferred_dtype=None):
    """2D convolution, NHWC x HWIO -> NHWC."""
    pad = _pad_pairs(padding, w.shape[:2], stride, x.shape[1:3])
    return lax.conv_general_dilated(
        x, w,
        window_strides=tuple(stride),
        padding=pad,
        rhs_dilation=tuple(dilation),
        dimension_numbers=DIMSPEC,
        feature_group_count=groups,
        preferred_element_type=preferred_dtype,
    )


def conv_output_size(in_size: int, kernel: int, stride: int, pad: int,
                     dilation: int = 1) -> int:
    """Output spatial size, strict mode (parity: util/ConvolutionUtils.java)."""
    eff_k = kernel + (kernel - 1) * (dilation - 1)
    return (in_size + 2 * pad - eff_k) // stride + 1


def same_pad(in_size: int, kernel: int, stride: int) -> int:
    out = -(-in_size // stride)
    total = max(0, (out - 1) * stride + kernel - in_size)
    return total // 2


def pool2d(x, kind: str, kernel=(2, 2), stride=(2, 2), padding: Padding = (0, 0),
           pnorm: int = 2):
    """Pooling, NHWC. kind in {max, avg, sum, pnorm}.

    Parity: reference SubsamplingLayer PoolingType {MAX, AVG, SUM, PNORM}.
    """
    kind = kind.lower()
    window = (1, *kernel, 1)
    strides = (1, *stride, 1)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        ph, pw = padding
        pad = ((0, 0), (ph, ph), (pw, pw), (0, 0))

    if kind == "max":
        init = -jnp.inf
        return lax.reduce_window(x, init, lax.max, window, strides, pad)
    if kind in ("avg", "sum"):
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
        if kind == "sum":
            return summed
        if pad == "VALID" or (not isinstance(pad, str) and all(p == (0, 0) for p in pad)):
            return summed / (kernel[0] * kernel[1])
        # divide by actual window sizes at borders (count_include_pad=False)
        ones = jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None]
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pad)
        return summed / counts
    if kind == "pnorm":
        p = float(pnorm)
        s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, pad)
        return s ** (1.0 / p)
    raise ValueError(f"unknown pooling type {kind!r}")


def lrn(x, k: float = 2.0, n: int = 5, alpha: float = 1e-4, beta: float = 0.75):
    """Cross-channel local response normalization on NHWC.

    y = x / (k + alpha * sum_{j in window(n)} x_j^2)^beta
    Parity: reference nn/conf/layers/LocalResponseNormalization.java:25-28
    (defaults n=5, k=2, alpha=1e-4, beta=0.75) and
    CudnnLocalResponseNormalizationHelper.
    """
    sq = x * x
    half = n // 2
    # sum over a window of n channels: reduce_window over the channel axis
    summed = lax.reduce_window(
        sq, 0.0, lax.add,
        window_dimensions=(1, 1, 1, n),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (0, 0), (0, 0), (half, n - 1 - half)),
    )
    return x / (k + alpha * summed) ** beta
