"""Convolution / pooling / LRN lowerings (NHWC, TPU-native layout).

Replaces the reference's im2col path (``nn/layers/convolution/
ConvolutionLayer.java:251`` preOutput) and the CudnnConvolutionHelper /
CudnnSubsamplingHelper / CudnnLocalResponseNormalizationHelper bindings
(``deeplearning4j-cuda/.../CudnnConvolutionHelper.java:48``): on TPU a single
``lax.conv_general_dilated`` HLO is tiled onto the MXU by XLA, and elementwise
pre/post ops fuse into it — no descriptor/workspace management needed.

Layouts: activations NHWC ``[batch, h, w, channels]``, kernels HWIO
``[kh, kw, in_c, out_c]``.
"""

from __future__ import annotations

import functools

from typing import Sequence, Tuple, Union

import jax
import numpy as np
from jax import lax
import jax.numpy as jnp

Padding = Union[str, Tuple[int, int]]

DIMSPEC = ("NHWC", "HWIO", "NHWC")


def _pad_pairs(padding: Padding, kernel, stride, in_hw):
    if isinstance(padding, str):
        return padding.upper()  # "SAME" / "VALID" handled by lax
    ph, pw = padding
    return ((ph, ph), (pw, pw))


def _conv2d_raw(x, w, stride=(1, 1), padding: Padding = (0, 0),
                dilation=(1, 1), groups: int = 1, preferred_dtype=None):
    pad = _pad_pairs(padding, w.shape[:2], stride, x.shape[1:3])
    return lax.conv_general_dilated(
        x, w,
        window_strides=tuple(stride),
        padding=pad,
        rhs_dilation=tuple(dilation),
        dimension_numbers=DIMSPEC,
        feature_group_count=groups,
        preferred_element_type=preferred_dtype,
    )


def conv2d(x, w, stride=(1, 1), padding: Padding = (0, 0), dilation=(1, 1),
           groups: int = 1, preferred_dtype=None):
    """2D convolution, NHWC x HWIO -> NHWC.

    ``DL4JTPU_CONV_DW=matmul`` (undilated/ungrouped convs only) switches the
    weight gradient to explicit [Cin, N·Ho·Wo] @ [N·Ho·Wo, Cout]
    contractions, one per kernel tap; dx keeps XLA's standard derivation.
    This is an alternative lowering shipped OFF: on v5e it measured ~33%
    slower than XLA's fused transposed-conv dW inside the ResNet-50 train
    step (63.3 vs 47.5 ms/step — PERF.md r4). Kept because it is exact
    (f64 parity suite in tests/test_convdw.py) and other TPU generations /
    conv mixes may rank the two differently.

    ``DL4JTPU_CONV_1X1=dot`` lowers 1x1 convs (no dilation/groups/padding)
    as channel contractions (``lax.dot_general``) instead of
    ``conv_general_dilated`` — stride>1 becomes a free slice first. Same
    math; a different HLO for XLA to schedule (PERF.md r5).
    """
    if (_1x1_mode() == "dot" and w.shape[0] == w.shape[1] == 1
            and groups == 1 and tuple(dilation) == (1, 1)
            and (isinstance(padding, str)  # SAME==VALID for a 1x1 kernel
                 or tuple(padding) == (0, 0))):
        sh, sw = tuple(stride)
        if sh > 1 or sw > 1:
            x = x[:, ::sh, ::sw, :]
        return lax.dot_general(
            x, w[0, 0], (((3,), (0,)), ((), ())),
            preferred_element_type=preferred_dtype)
    if (_dw_mode() == "matmul" and groups == 1
            and tuple(dilation) == (1, 1)):
        return _conv2d_mmdw(x, w, tuple(stride), padding, preferred_dtype)
    return _conv2d_raw(x, w, stride, padding, dilation, groups,
                       preferred_dtype)


def _1x1_mode() -> str:
    import os
    return os.environ.get("DL4JTPU_CONV_1X1", "")


def _dw_mode() -> str:
    import os
    return os.environ.get("DL4JTPU_CONV_DW", "")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _conv2d_mmdw(x, w, stride, padding, preferred_dtype):
    return _conv2d_raw(x, w, stride, padding, (1, 1), 1, preferred_dtype)


def _conv2d_mmdw_fwd(x, w, stride, padding, preferred_dtype):
    return _conv2d_mmdw(x, w, stride, padding, preferred_dtype), (x, w)


def _conv2d_mmdw_bwd(stride, padding, preferred_dtype, res, dy):
    x, w = res
    # dx: XLA's standard transposed-conv derivation; linear_transpose (not
    # vjp) so the eager backward doesn't re-execute the discarded primal
    dx, = jax.linear_transpose(
        lambda xx: _conv2d_raw(xx, w, stride, padding, (1, 1), 1,
                               preferred_dtype), x)(dy)
    # dW: one tall-skinny matmul per kernel tap
    kh, kw, cin, cout = w.shape
    sh, sw = stride
    n, ho, wo, _ = dy.shape
    pad = _pad_pairs(padding, (kh, kw), stride, x.shape[1:3])
    if isinstance(pad, str):
        # exactly XLA's SAME/VALID lo/hi split
        pads = lax.padtype_to_pads(x.shape[1:3], (kh, kw), (sh, sw), pad)
    else:
        pads = list(pad)
    # lax.pad, not jnp.pad: eager jnp.pad returns uninitialized memory on the
    # forced-multi-device CPU backend used by the test mesh (jax 0.9.0)
    xp = lax.pad(x, jnp.zeros((), x.dtype),
                 ((0, 0, 0), (*pads[0], 0), (*pads[1], 0), (0, 0, 0)))
    dy2 = dy.reshape(n * ho * wo, cout)
    taps = []
    for p in range(kh):
        for q in range(kw):
            # input window feeding output pixel (h,w) through tap (p,q)
            xs = lax.slice(xp, (0, p, q, 0),
                           (n, p + (ho - 1) * sh + 1, q + (wo - 1) * sw + 1,
                            cin), (1, sh, sw, 1))
            x2 = xs.reshape(n * ho * wo, cin)
            taps.append(lax.dot_general(
                x2, dy2, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.promote_types(x.dtype,
                                                         jnp.float32)))
    dw = jnp.stack(taps).reshape(kh, kw, cin, cout).astype(w.dtype)
    return dx, dw


_conv2d_mmdw.defvjp(_conv2d_mmdw_fwd, _conv2d_mmdw_bwd)


def conv_output_size(in_size: int, kernel: int, stride: int, pad: int,
                     dilation: int = 1) -> int:
    """Output spatial size, strict mode (parity: util/ConvolutionUtils.java)."""
    eff_k = kernel + (kernel - 1) * (dilation - 1)
    return (in_size + 2 * pad - eff_k) // stride + 1


def same_pad(in_size: int, kernel: int, stride: int) -> int:
    out = -(-in_size // stride)
    total = max(0, (out - 1) * stride + kernel - in_size)
    return total // 2


def pool2d(x, kind: str, kernel=(2, 2), stride=(2, 2), padding: Padding = (0, 0),
           pnorm: int = 2):
    """Pooling, NHWC. kind in {max, avg, sum, pnorm}.

    Parity: reference SubsamplingLayer PoolingType {MAX, AVG, SUM, PNORM}.
    """
    kind = kind.lower()
    window = (1, *kernel, 1)
    strides = (1, *stride, 1)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        ph, pw = padding
        pad = ((0, 0), (ph, ph), (pw, pw), (0, 0))

    if kind == "max":
        init = -jnp.inf
        return lax.reduce_window(x, init, lax.max, window, strides, pad)
    if kind in ("avg", "sum"):
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
        if kind == "sum":
            return summed
        if pad == "VALID" or (not isinstance(pad, str) and all(p == (0, 0) for p in pad)):
            return summed / (kernel[0] * kernel[1])
        # divide by actual window sizes at borders (count_include_pad=False)
        ones = jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None]
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pad)
        return summed / counts
    if kind == "pnorm":
        p = float(pnorm)
        s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, pad)
        return s ** (1.0 / p)
    raise ValueError(f"unknown pooling type {kind!r}")


def lrn(x, k: float = 2.0, n: int = 5, alpha: float = 1e-4, beta: float = 0.75):
    """Cross-channel local response normalization on NHWC.

    y = x / (k + alpha * sum_{j in window(n)} x_j^2)^beta
    Parity: reference nn/conf/layers/LocalResponseNormalization.java:25-28
    (defaults n=5, k=2, alpha=1e-4, beta=0.75) and
    CudnnLocalResponseNormalizationHelper.
    """
    sq = x * x
    half = n // 2
    # sum over a window of n channels: reduce_window over the channel axis
    summed = lax.reduce_window(
        sq, 0.0, lax.add,
        window_dimensions=(1, 1, 1, n),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (0, 0), (0, 0), (half, n - 1 - half)),
    )
    return x / (k + alpha * summed) ** beta
