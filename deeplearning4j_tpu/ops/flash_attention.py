"""Pallas flash-attention for TPU — forward AND backward kernels.

Forward: the [t, t] score matrix never exists anywhere. The grid holds one
[block_q, block_k] logits tile at a time; per-q-block online-softmax
accumulators live in VMEM. Two variants auto-dispatched on K/V size:
whole-K/V-in-VMEM with a dynamic fori_loop that SKIPS post-diagonal blocks
(loads and compute) in the causal case, and a grid-streamed variant
(O(block) VMEM) for longer sequences. The kernel also emits the per-row
log-sum-exp, which makes the backward blockwise too.

Key-validity masks ([b, t_kv], 1=attend) are supported: masked keys get
NEG_INF logits, and rows with NO attendable keys (leading padding under a
causal mask, all-zero mask rows) output 0 — same semantics as the guarded
XLA path in ``ops.attention``.

Backward: Pallas kernels for both passes — P is recomputed per tile from
the saved lse; the dq pass streams K/V blocks while the dq tile
accumulates in VMEM scratch; the fused dk/dv pass recomputes each tile's
P/dS once for both grads. Peak memory is O(t·block + t·d), so TRAINING
runs at sequence lengths where XLA's attention cannot even compile.
Gradients match the dense path (CPU interpret + on-chip parity tests).
A JAX-blockwise fallback backward remains behind ``DL4JTPU_FLASH_BWD=jax``.

Measured numbers live in PERF.md ("Pallas flash attention" + "Pallas
backward kernels" sections — the single source of truth): fwd+grad
2.2-2.3× over the XLA fused path at t≥4096 (forward alone 1.8-2.8×), and
t=16384 runs fwd+bwd where XLA OOMs.

Routing (``ops.attention.dot_product_attention``): auto at t ≥ 4096 on
the TPU backend; ``DL4JTPU_FLASH_ATTENTION=1`` forces it on (any length),
``0`` forces the XLA path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_HALF_NEG = NEG_INF / 2
# whole-K/V-in-VMEM variant above this size switches to the grid-streamed
# kernel (module constant so tests can force the streamed path)
_VMEM_KV_LIMIT = 4 * 1024 * 1024


def _masked_update(q, k, v, valid, m_prev, num, den, *, scale, causal,
                   block_q, block_k, q_offset, k_offset):
    """One online-softmax block update with NEG_INF-sentinel guards:
    rows whose running max is still NEG_INF (no attendable key yet)
    contribute exactly zero — so fully-masked rows end at num=den=0."""
    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale       # [bq, bk]
    logits = jnp.where(valid, logits, NEG_INF)   # valid: [1, bk] bool
    if causal:
        rows = q_offset + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = k_offset + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        logits = jnp.where(rows >= cols, logits, NEG_INF)
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1))
    m_safe = jnp.where(m_new <= _HALF_NEG, 0.0, m_new)
    p = jnp.where(logits <= _HALF_NEG, 0.0,
                  jnp.exp(logits - m_safe[:, None]))
    corr = jnp.where(m_prev <= _HALF_NEG, 0.0,
                     jnp.exp(m_prev - m_safe))
    num = num * corr[:, None] + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    den = den * corr + jnp.sum(p, axis=-1)
    return m_new, num, den


def _finalize(m, num, den):
    """(out, lse) from the accumulators; 0-key rows → out 0, lse NEG_INF."""
    out = num / jnp.maximum(den, 1e-30)[:, None]
    lse = jnp.where(den > 0, m + jnp.log(jnp.maximum(den, 1e-30)), NEG_INF)
    return out, lse


# --------------------------------------------------------------------------
# forward kernels
# --------------------------------------------------------------------------


def _fwd_kernel_vmem(q_ref, k_ref, v_ref, mk_ref, o_ref, lse_ref, *,
                     scale, causal, block_q, block_k):
    """Whole-K/V-in-VMEM variant: one DMA brings K/V in, then a fori_loop
    over k-blocks runs the online softmax. The dynamic loop bound skips
    post-diagonal blocks entirely (loads and compute) when causal."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                  # [block_q, d]
    t = k_ref.shape[1]
    d = q.shape[-1]

    def body(j, carry):
        m_prev, num, den = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        valid = mk_ref[0, pl.ds(j, 1), :] > 0     # [1, block_k]
        return _masked_update(q, k, v, valid, m_prev, num, den,
                              scale=scale, causal=causal, block_q=block_q,
                              block_k=block_k, q_offset=qi * block_q,
                              k_offset=j * block_k)

    if causal:
        nk = (qi * block_q + block_q + block_k - 1) // block_k
    else:
        nk = t // block_k
    init = (jnp.full((block_q,), NEG_INF, jnp.float32),
            jnp.zeros((block_q, d), jnp.float32),
            jnp.zeros((block_q,), jnp.float32))
    m, num, den = jax.lax.fori_loop(0, nk, body, init)
    out, lse = _finalize(m, num, den)
    o_ref[0] = out.astype(o_ref.dtype)
    lse_ref[0, :, 0] = lse


def _fwd_kernel_stream(q_ref, k_ref, v_ref, mk_ref, o_ref, lse_ref, m_s,
                       num_s, den_s, *, scale, causal, block_q, block_k,
                       nk):
    """Grid-streamed variant: pallas double-buffers K/V blocks through
    VMEM; online-softmax accumulators persist in VMEM scratch across the
    (sequential) k dimension of the grid."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        num_s[...] = jnp.zeros_like(num_s)
        den_s[...] = jnp.zeros_like(den_s)

    relevant = (kj * block_k <= qi * block_q + block_q - 1) if causal \
        else (kj >= 0)

    @pl.when(relevant)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)              # [bq, d]
        k = k_ref[0].astype(jnp.float32)              # [bk, d]
        v = v_ref[0].astype(jnp.float32)              # [bk, d]
        valid = mk_ref[0, pl.ds(kj, 1), :] > 0    # [1, block_k]
        m, num, den = _masked_update(
            q, k, v, valid, m_s[...][:, 0], num_s[...], den_s[...][:, 0],
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            q_offset=qi * block_q, k_offset=kj * block_k)
        m_s[...] = m[:, None]
        num_s[...] = num
        den_s[...] = den[:, None]

    @pl.when(kj == nk - 1)
    def _final():
        out, lse = _finalize(m_s[...][:, 0], num_s[...], den_s[...][:, 0])
        o_ref[0] = out.astype(o_ref.dtype)
        lse_ref[0, :, 0] = lse


def _flash_fwd_btd(qt, kt, vt, mask_bt, *, n_heads, scale, causal,
                   block_q, interpret, block_k: int = 512,
                   auto_tile: bool = False):
    """[bh, t, d] q/k/v + [b, t] key mask → ([bh, t, d] out, [bh, t] lse).
    The mask is NOT head-folded: index maps read row ``bh // n_heads``, so
    one [b, ...] mask array serves every head."""
    bh, t, d = qt.shape
    if t % block_q:
        raise ValueError(
            f"flash_attention needs t % block_q == 0 (t={t}, "
            f"block_q={block_q}) — unwritten tail blocks would return "
            "uninitialized memory; use the XLA path for ragged lengths")
    if auto_tile:
        # default-tile callers get wider q tiles when t allows (512 rows
        # measured ~10% faster at f32-4096 and bf16-8192, d=128); an
        # EXPLICIT block_q is never overridden, and the upgrade is skipped
        # when the q/num tile would exceed ~512KB VMEM (large head dims)
        for wider in (512, 256):
            if (wider > block_q and t % wider == 0
                    and wider * d * 4 <= 512 * 1024):
                block_q = wider
                break
    if t % block_k:
        block_k = block_q
    nk = t // block_k
    # mask rides pre-blocked as [b, t//block_k, block_k]: each kernel step
    # slices one native (1, block_k) row — no vector reshapes (Mosaic
    # rejects rank changes), no lane padding ([bh, t, 1] OOM'd VMEM), no
    # lane-dim dynamic slicing ([bh, 1, t] measured ~10x slower). Both
    # variants take the FULL per-batch-row mask block (t floats — trivially
    # VMEM-resident) because a (1, 1, block_k) partial block would violate
    # the (8, 128)-or-full tiling rule on the middle dim.
    mkt = mask_bt.astype(jnp.float32).reshape(-1, nk, block_k)
    h_ = n_heads
    # lse rides as [bh, t, 1]: TPU block shapes need the last two dims
    # (8, 128)-aligned or full — (block_q, 1) satisfies that, (1, block_q)
    # does not
    out_shapes = (jax.ShapeDtypeStruct((bh, t, d), qt.dtype),
                  jax.ShapeDtypeStruct((bh, t, 1), jnp.float32))
    out_specs = (pl.BlockSpec((1, block_q, d), lambda b, i, *j: (b, i, 0)),
                 pl.BlockSpec((1, block_q, 1), lambda b, i, *j: (b, i, 0)))
    kv_bytes = 2 * t * d * qt.dtype.itemsize
    if kv_bytes <= _VMEM_KV_LIMIT:
        kernel = functools.partial(_fwd_kernel_vmem, scale=scale,
                                   causal=causal, block_q=block_q,
                                   block_k=block_k)
        out, lse = pl.pallas_call(
            kernel,
            grid=(bh, t // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, nk, block_k),
                             lambda b, i: (b // h_, 0, 0)),
            ],
            out_specs=out_specs,
            out_shape=out_shapes,
            interpret=interpret,
        )(qt, kt, vt, mkt)
        return out, lse[..., 0]
    kernel = functools.partial(_fwd_kernel_stream, scale=scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k, nk=nk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, t // block_q, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, nk, block_k), lambda b, i, j: (b // h_, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),    # running max
            pltpu.VMEM((block_q, d), jnp.float32),    # numerator
            pltpu.VMEM((block_q, 1), jnp.float32),    # denominator
        ],
        interpret=interpret,
    )(qt, kt, vt, mkt)
    return out, lse[..., 0]


# --------------------------------------------------------------------------
# blockwise backward (flash backward in plain JAX — tiles via lax.scan)
# --------------------------------------------------------------------------


def _flash_bwd_btd(q, k, v, mk, out, lse, dout, *, scale, causal, block_q,
                   block_k):
    """[bh, t, d] grads with O(t·block + t·d) peak memory.

    Standard flash backward: P recomputed per tile from the saved lse,
    dS = P ∘ (dout·vᵀ − Δ), Δ = rowsum(dout ∘ out). Two passes, each
    parallel (vmapped) over one block axis and sequential over the other,
    so XLA batches the tile matmuls instead of serializing them."""
    bh, t, d = q.shape
    if t % block_k:
        block_k = block_q
    nq, nk = t // block_q, t // block_k
    f32 = functools.partial(jnp.asarray, dtype=jnp.float32)
    delta = jnp.sum(f32(dout) * f32(out), axis=-1)        # [bh, t]
    i_base = jnp.arange(nq) * block_q
    j_base = jnp.arange(nk) * block_k
    r_iota = jnp.arange(block_q)
    c_iota = jnp.arange(block_k)

    def _p_ds(qi, kj, vj, mj, doi, lsei, deltai, i0, j0):
        """Recompute one [block_q, block_k] tile's P and dS. Rows with
        lse=NEG_INF (no attendable keys) get P=0, not exp(overflow)."""
        s = jnp.dot(qi, kj.T, preferred_element_type=jnp.float32) * scale
        lse_safe = jnp.where(lsei <= _HALF_NEG, 0.0, lsei)
        p = jnp.where((lsei <= _HALF_NEG)[:, None], 0.0,
                      jnp.exp(s - lse_safe[:, None]))
        p = jnp.where((mj > 0)[None, :], p, 0.0)
        if causal:
            allow = (i0 + r_iota)[:, None] >= (j0 + c_iota)[None, :]
            p = jnp.where(allow, p, 0.0)
        dp = jnp.dot(doi, vj.T, preferred_element_type=jnp.float32)
        ds = p * (dp - deltai[:, None]) * scale
        return p, ds

    def per_head(q, k, v, mk, lse, delta, dout):
        q_r = f32(q).reshape(nq, block_q, d)
        k_r = f32(k).reshape(nk, block_k, d)
        v_r = f32(v).reshape(nk, block_k, d)
        m_r = f32(mk).reshape(nk, block_k)
        do_r = f32(dout).reshape(nq, block_q, d)
        lse_r = lse.reshape(nq, block_q)
        dl_r = delta.reshape(nq, block_q)

        def dq_block(qi, doi, lsei, deltai, i0):
            def over_j(dqi, xs):
                kj, vj, mj, j0 = xs
                _, ds = _p_ds(qi, kj, vj, mj, doi, lsei, deltai, i0, j0)
                return dqi + jnp.dot(ds, kj,
                                     preferred_element_type=jnp.float32), None
            dqi, _ = jax.lax.scan(over_j,
                                  jnp.zeros((block_q, d), jnp.float32),
                                  (k_r, v_r, m_r, j_base))
            return dqi

        def dkv_block(kj, vj, mj, j0):
            def over_i(carry, xs):
                dkj, dvj = carry
                qi, doi, lsei, deltai, i0 = xs
                p, ds = _p_ds(qi, kj, vj, mj, doi, lsei, deltai, i0, j0)
                dkj = dkj + jnp.dot(ds.T, qi,
                                    preferred_element_type=jnp.float32)
                dvj = dvj + jnp.dot(p.T, doi,
                                    preferred_element_type=jnp.float32)
                return (dkj, dvj), None
            (dkj, dvj), _ = jax.lax.scan(
                over_i, (jnp.zeros((block_k, d), jnp.float32),
                         jnp.zeros((block_k, d), jnp.float32)),
                (q_r, do_r, lse_r, dl_r, i_base))
            return dkj, dvj

        dq = jax.vmap(dq_block)(q_r, do_r, lse_r, dl_r, i_base)
        dk, dv = jax.vmap(dkv_block)(k_r, v_r, m_r, j_base)
        return (dq.reshape(t, d), dk.reshape(t, d), dv.reshape(t, d))

    dq, dk, dv = jax.vmap(per_head)(q, k, v, mk, lse, delta, dout)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------------
# Pallas backward kernels: dq pass + fused dk/dv pass
# --------------------------------------------------------------------------


def _bwd_p_ds(q, k, v, do, lse, delta, valid, *, scale, causal,
              q_offset, k_offset, block_q, block_k):
    """Recompute one [block_q, block_k] tile's (P, dS) from the saved lse
    (standard flash backward). Rows with lse=NEG_INF (no attendable keys)
    get P=0, not exp(overflow)."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    lse_safe = jnp.where(lse <= _HALF_NEG, 0.0, lse)
    p = jnp.where((lse <= _HALF_NEG)[:, None], 0.0,
                  jnp.exp(s - lse_safe[:, None]))
    p = jnp.where(valid, p, 0.0)                    # valid: [1, bk] bool
    if causal:
        rows = q_offset + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = k_offset + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        p = jnp.where(rows >= cols, p, 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None]) * scale
    return p, ds


def _bwd_dq_kernel(q_ref, k_ref, v_ref, mk_ref, lse_ref, dl_ref, do_ref,
                   dq_ref, dq_acc, *, scale, causal, block_q, block_k, nk):
    """dq pass: grid (bh, nq, nk), k sequential — the dq tile accumulates
    in VMEM scratch while Pallas streams (double-buffers) K/V blocks."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    relevant = (kj * block_k <= qi * block_q + block_q - 1) if causal \
        else (kj >= 0)

    @pl.when(relevant)
    def _accumulate():
        _, ds = _bwd_p_ds(
            q_ref[0].astype(jnp.float32), k_ref[0].astype(jnp.float32),
            v_ref[0].astype(jnp.float32), do_ref[0].astype(jnp.float32),
            lse_ref[0, :, 0], dl_ref[0, :, 0],
            mk_ref[0, pl.ds(kj, 1), :] > 0,
            scale=scale, causal=causal, q_offset=qi * block_q,
            k_offset=kj * block_k, block_q=block_q, block_k=block_k)
        dq_acc[...] += jax.lax.dot_general(
            ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _write():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, mk_ref, q_ref, lse_ref, dl_ref, do_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, nq):
    """Fused dk/dv pass: grid (bh, nk, nq), q sequential — P and dS are
    recomputed ONCE per tile and feed both dk (dSᵀ·q) and dv (Pᵀ·dout)."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    relevant = (qi * block_q + block_q - 1 >= kj * block_k) if causal \
        else (qi >= 0)

    @pl.when(relevant)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        p, ds = _bwd_p_ds(
            q, k_ref[0].astype(jnp.float32), v_ref[0].astype(jnp.float32),
            do, lse_ref[0, :, 0], dl_ref[0, :, 0],
            mk_ref[0, pl.ds(kj, 1), :] > 0,
            scale=scale, causal=causal, q_offset=qi * block_q,
            k_offset=kj * block_k, block_q=block_q, block_k=block_k)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _write():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_btd_pallas(q, k, v, mk, out, lse, dout, *, scale, causal,
                          block_q, block_k, interpret, n_heads):
    """[bh, t, d] grads via the two Pallas passes. Same math as
    ``_flash_bwd_btd`` (the JAX-blockwise fallback, kept for
    ``DL4JTPU_FLASH_BWD=jax``) with the tile loops lowered to Mosaic:
    measured ≥1.5× over the XLA backward at bf16 t=8192 (PERF.md)."""
    bh, t, d = q.shape
    if t % block_k:
        block_k = block_q
    nq, nk = t // block_q, t // block_k
    h_ = n_heads
    # delta = rowsum(dout * out): one cheap fused elementwise pass in XLA
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[..., None]                       # [bh, t, 1]
    lse3 = lse[..., None]                                     # [bh, t, 1]
    mkt = mk.astype(jnp.float32).reshape(-1, nk, block_k)

    i_spec = lambda name: pl.BlockSpec((1, block_q, d),
                                       lambda b, i, j: (b, i, 0))
    i_col = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    if causal:
        # clamp the streamed K/V index map at the causal diagonal: the
        # grid still visits post-diagonal steps (compute is pl.when-gated
        # off), but a repeated block index lets Pallas elide the DMA —
        # the backward analog of the forward kernel's loads-and-compute
        # skip, halving streamed traffic at large t
        def _kv_map(b, i, j):
            return (b, jnp.minimum(
                j, (i * block_q + block_q - 1) // block_k), 0)
        j_spec = pl.BlockSpec((1, block_k, d), _kv_map)
    else:
        j_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    mk_spec = pl.BlockSpec((1, nk, block_k), lambda b, i, j: (b // h_, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk),
        grid=(bh, nq, nk),
        in_specs=[i_spec("q"), j_spec, j_spec, mk_spec, i_col, i_col,
                  i_spec("do")],
        out_specs=i_spec("dq"),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, mkt, lse3, delta, dout)

    # dk/dv pass: i (q-blocks) is the SEQUENTIAL (last) grid dim
    jk_spec = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    if causal:
        # pre-diagonal q blocks contribute nothing to this k block —
        # clamp their index map to the first relevant block (fetched
        # once, then reused) so the skipped steps cost no DMA
        def _q_map(b, j, i):
            return (b, jnp.maximum(i, (j * block_k) // block_q), 0)

        def _q_col_map(b, j, i):
            return (b, jnp.maximum(i, (j * block_k) // block_q), 0)
        iq_spec = pl.BlockSpec((1, block_q, d), _q_map)
        iq_col = pl.BlockSpec((1, block_q, 1), _q_col_map)
    else:
        iq_spec = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
        iq_col = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0))
    mk2_spec = pl.BlockSpec((1, nk, block_k),
                            lambda b, j, i: (b // h_, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq),
        grid=(bh, nk, nq),
        in_specs=[jk_spec, jk_spec, mk2_spec, iq_spec, iq_col, iq_col,
                  iq_spec],
        out_specs=(jk_spec, jk_spec),
        out_shape=(jax.ShapeDtypeStruct((bh, t, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, t, d), v.dtype)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(k, v, mkt, q, lse3, delta, dout)
    return dq, dk, dv


# --------------------------------------------------------------------------
# block-callable entry: online-softmax carry across flash calls
# --------------------------------------------------------------------------
#
# The ring sequence-parallel path (ops.attention.ring_attention) holds one
# local Q shard and sees K/V one visiting shard per hop.  These three
# functions let each hop run the SAME Pallas forward kernel on (local q,
# visiting k/v) and fold the hop's result into an online-softmax carry
# (running max ``m``, normalizer ``l``, accumulator ``o``), so the
# full-sequence softmax is exact without the [t, t] matrix ever existing —
# on any device, at any hop.  Cross-hop causal masking is resolved by the
# CALLER into one of two static kernel modes (every hop pair is either
# entirely pre-diagonal → ``causal=False``, on the diagonal →
# ``causal=True``, or entirely post-diagonal → skipped), so the kernels
# never need dynamic global offsets.


def flash_carry_init(q):
    """Fresh (m, l, o) carry for a [b, t, h, d] query block: running max
    ``m`` [b,t,h] at NEG_INF, normalizer ``l`` [b,t,h] at 0, accumulator
    ``o`` [b,t,h,d] at 0 — all float32 regardless of q's dtype (the carry
    is the accumulation domain)."""
    b, t, h, d = q.shape
    return (jnp.full((b, t, h), NEG_INF, jnp.float32),
            jnp.zeros((b, t, h), jnp.float32),
            jnp.zeros((b, t, h, d), jnp.float32))


def flash_attention_block(q, k, v, carry, *, causal=False, scale=None,
                          mask=None, block_q=None, interpret=False):
    """One carry update: flash-tiled attention of q [b,tq,h,d] against ONE
    k/v block [b,tk,h,d], folded into ``carry`` (from
    :func:`flash_carry_init` or a previous call).  The Pallas forward
    kernel does the tiled work and emits this block's (out, lse); the fold
    is the standard log-space online-softmax merge, exact and
    order-independent.

    ``causal=True`` means q and k/v occupy the SAME global time offset
    (the diagonal block); pre-diagonal blocks are ``causal=False`` and
    post-diagonal blocks must simply not be fed.  ``mask``: optional
    [b, tk] key-validity for THIS block.  Rows that have seen no
    attendable key anywhere keep m=NEG_INF / l=0 and finalize to 0."""
    m, l, o = carry
    b, t, h, d = q.shape
    if k.shape[1] != t:
        raise ValueError(
            f"flash_attention_block needs len(k) == len(q) (got "
            f"{k.shape[1]} vs {t}) — ring hops are shard-sized; pad the "
            "shorter side under a key mask instead")
    if mask is None:
        mask = jnp.ones((k.shape[0], k.shape[1]), jnp.float32)
    out_h, lse_h = _core_fwd(q, k, v, jnp.asarray(mask, jnp.float32),
                             causal, scale, block_q, interpret)
    lse_h = lse_h.reshape(b, h, t).transpose(0, 2, 1)       # [b, t, h]
    m_new = jnp.maximum(m, lse_h)
    m_safe = jnp.where(m_new <= _HALF_NEG, 0.0, m_new)
    corr = jnp.where(m <= _HALF_NEG, 0.0, jnp.exp(m - m_safe))
    w = jnp.where(lse_h <= _HALF_NEG, 0.0, jnp.exp(lse_h - m_safe))
    o = o * corr[..., None] + out_h.astype(jnp.float32) * w[..., None]
    l = l * corr + w
    return m_new, l, o


def flash_carry_finalize(carry):
    """(out [b,t,h,d] f32, lse [b,t,h] f32) from an (m, l, o) carry.
    Rows that never saw an attendable key → out 0, lse NEG_INF — the same
    semantics as the monolithic kernel."""
    m, l, o = carry
    out = o / jnp.maximum(l, 1e-30)[..., None]
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)
    return out, lse


def flash_attention_bwd_block(q, k, v, out, lse, dout, *, causal=False,
                              scale=None, mask=None, block_q=None,
                              interpret=False):
    """Per-block flash backward for the ring VJP: given the FINAL output,
    its cotangent, and the FULL-sequence lse (all [b,tq,h,...], from
    :func:`flash_carry_finalize`), return this (q, k/v)-block pair's
    (dq, dk, dv) contributions — the standard flash backward recomputes P
    per tile from the global lse, so per-block contributions sum exactly
    to the dense gradient.  Same Pallas kernels as the monolithic
    backward; ``DL4JTPU_FLASH_BWD=jax`` selects the lax.scan blockwise
    fallback (read at trace time, like the monolithic path).  ``causal``
    has the same diagonal-block meaning as :func:`flash_attention_block`."""
    import os
    b, t, h, d = q.shape
    if k.shape[1] != t:
        raise ValueError(
            f"flash_attention_bwd_block needs len(k) == len(q) (got "
            f"{k.shape[1]} vs {t}) — ring hops are shard-sized")
    s = _resolve_scale(scale, d)
    if mask is None:
        mask = jnp.ones((k.shape[0], k.shape[1]), jnp.float32)
    mask = jnp.asarray(mask, jnp.float32)
    to_btd = lambda a: a.transpose(0, 2, 1, 3).reshape(
        a.shape[0] * a.shape[2], a.shape[1], a.shape[3])
    lse_b = lse.transpose(0, 2, 1).reshape(b * h, t)
    use_jax = os.environ.get("DL4JTPU_FLASH_BWD") == "jax"
    bq_bwd, bk_bwd = _bwd_tiles(t, block_q, pallas=not use_jax)
    if use_jax:
        mk = jnp.repeat(mask, h, axis=0)
        dq, dk, dv = _flash_bwd_btd(
            to_btd(q), to_btd(k), to_btd(v), mk, to_btd(out), lse_b,
            to_btd(dout), scale=s, causal=causal, block_q=bq_bwd,
            block_k=bk_bwd)
    else:
        dq, dk, dv = _flash_bwd_btd_pallas(
            to_btd(q), to_btd(k), to_btd(v), mask, to_btd(out), lse_b,
            to_btd(dout), scale=s, causal=causal, block_q=bq_bwd,
            block_k=bk_bwd, interpret=interpret, n_heads=h)
    back = lambda a, tt: a.reshape(b, h, tt, d).transpose(0, 2, 1, 3)
    return back(dq, t), back(dk, k.shape[1]), back(dv, k.shape[1])


# --------------------------------------------------------------------------
# public op with custom_vjp
# --------------------------------------------------------------------------


def _resolve_scale(scale, d):
    return scale if scale is not None else 1.0 / float(d) ** 0.5


def _bwd_tiles(t, block_q, pallas):
    """Backward tile choice — ONE copy of the PERF.md sweep rationale for
    both the monolithic VJP and the ring's per-hop backward. Pallas
    kernels take 512×1024 when t allows (fastest point that fits the
    16MB scoped-VMEM limit; 1024² OOMs, 256² is ~2× slower); the
    lax.scan fallback has no VMEM ceiling, so it takes square 1024
    tiles. ``block_q`` is the FALLBACK tile for non-divisible t (the
    caller's forward/padding granule), not an override of the tuned
    table."""
    if t % 1024 == 0:
        return (512, 1024) if pallas else (1024, 1024)
    if t % 512 == 0:
        return 512, 512
    if pallas and t % 256 == 0:
        return 256, 256
    bq = block_q or 128
    return bq, bq


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_core(q, k, v, mask, causal, scale, block_q, interpret):
    out, _ = _core_fwd(q, k, v, mask, causal, scale, block_q, interpret)
    return out


def _core_fwd(q, k, v, mask, causal, scale, block_q, interpret):
    b, t, h, d = q.shape
    s = _resolve_scale(scale, d)
    to_btd = lambda a: a.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    out, lse = _flash_fwd_btd(to_btd(q), to_btd(k), to_btd(v), mask,
                              n_heads=h, scale=s, causal=causal,
                              block_q=block_q or 128, interpret=interpret,
                              auto_tile=block_q is None)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3), lse


def _core_fwd_rule(q, k, v, mask, causal, scale, block_q, interpret):
    out, lse = _core_fwd(q, k, v, mask, causal, scale, block_q, interpret)
    return out, (q, k, v, mask, out, lse)


def _core_bwd_rule(causal, scale, block_q, interpret, res, g):
    import os
    q, k, v, mask, out, lse = res
    b, t, h, d = q.shape
    s = _resolve_scale(scale, d)
    to_btd = lambda a: a.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    use_jax = os.environ.get("DL4JTPU_FLASH_BWD") == "jax"
    bq_bwd, bk_bwd = _bwd_tiles(t, block_q, pallas=not use_jax)
    if use_jax:
        # JAX-blockwise fallback (same math, lax.scan tiles)
        mk = jnp.repeat(mask.astype(jnp.float32), h, axis=0)
        dq, dk, dv = _flash_bwd_btd(
            to_btd(q), to_btd(k), to_btd(v), mk, to_btd(out), lse,
            to_btd(g), scale=s, causal=causal, block_q=bq_bwd,
            block_k=bk_bwd)
    else:
        # tile choice: see _bwd_tiles (the PERF.md sweep rationale)
        dq, dk, dv = _flash_bwd_btd_pallas(
            to_btd(q), to_btd(k), to_btd(v), mask, to_btd(out), lse,
            to_btd(g), scale=s, causal=causal, block_q=bq_bwd,
            block_k=bk_bwd, interpret=interpret, n_heads=h)
    back = lambda a: a.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return back(dq), back(dk), back(dv), jnp.zeros_like(mask,
                                                        dtype=jnp.float32)


_flash_core.defvjp(_core_fwd_rule, _core_bwd_rule)


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    interpret=None, mask=None):
    """[b, t, h, d] attention with Pallas forward and backward kernels
    (``DL4JTPU_FLASH_BWD=jax`` selects the lax.scan blockwise backward
    instead). t must divide by ``block_q`` (default: auto — 128-row
    granularity, upgraded to wider tiles when t and the VMEM budget allow;
    an explicit ``block_q`` is used as-is). ``mask``: optional [b, t_kv]
    key-validity mask (1=attend); rows with no attendable keys output 0.
    ``interpret``: None = auto at trace time — interpret-mode off-TPU, so
    ``DL4JTPU_FLASH_ATTENTION=1`` exercises the kernel math on the CPU
    test backend too."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if mask is None:
        mask = jnp.ones((q.shape[0], q.shape[1]), jnp.float32)
    return _flash_core(q, k, v, jnp.asarray(mask, jnp.float32), causal,
                       scale, block_q, interpret)


def flash_available(q_shape, mask, block_q: int = 128) -> bool:
    """Should the Pallas path serve this call?

    ``DL4JTPU_FLASH_ATTENTION``: ``1`` forces it on, ``0`` off; unset =
    auto — on for t ≥ 4096 on the TPU backend (where it measures ≥2× over
    the XLA path on v5e; below that XLA's fusion already sits at the
    memory floor). Non-multiple-of-block lengths always use the XLA path.

    NOTE: this runs at *trace* time. The chosen route is baked into any
    already-compiled jit — set the flag before the first trace of a step
    function (or clear jit caches via ``fn.clear_cache()`` /
    ``jax.clear_caches()``) for a toggle to take effect."""
    import os
    flag = os.environ.get("DL4JTPU_FLASH_ATTENTION", "auto")
    if flag == "0" or q_shape[1] % block_q:
        return False
    if mask is not None and getattr(mask, "shape", None) is not None \
            and tuple(mask.shape) != (q_shape[0], q_shape[1]):
        return False   # only [b, t_kv] key masks map onto the kernel
    if flag == "1":
        return True
    return q_shape[1] >= 4096 and jax.devices()[0].platform == "tpu"
