"""Fused batch-norm training kernel (forward + hand-written VJP).

Role parity: the reference reaches cuDNN's fused BN through
``CudnnBatchNormalizationHelper.java`` (``deeplearning4j-cuda/src/main/java/org/
deeplearning4j/nn/layers/normalization/CudnnBatchNormalizationHelper.java``).
On TPU the autodiff backward of a naive BN is the expensive path: XLA derives
a chain of full-tensor f32 intermediates (upcast, mean/var VJPs) that cost
several extra HBM passes over the activation. Profiling ResNet-50 showed BN
at ~27 ms of a 57 ms train step. This module replaces it with the standard
two-pass formulation and a custom VJP:

  forward:  one fused pass for the f32-accumulated sums (mean, E[x^2]),
            one pass to normalize in the activation dtype.
  backward: one fused pass for (dbeta, dgamma), one pass for dx —
            the textbook BN gradient, all elementwise work in the activation
            dtype, reductions accumulated in the stats dtype.

The custom VJP wraps ONLY the normalized output ``y``; batch mean/var for
the running-average update are computed by plain (aux, non-differentiated)
ops outside the custom boundary, and XLA CSE merges them with the identical
stats computed inside the forward. Returning them from the custom_vjp
instead would hand the backward *materialized zero* cotangents for mean/var
and burn two full-tensor multiply-adds of zeros per BN layer per step
(measured ~4 ms/step on ResNet-50 batch 128).

Stats reduce over all axes except the last (channel) axis — NHWC and [b, f]
both work.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _reduce_axes(x):
    return tuple(range(x.ndim - 1))


def _n_elements(x) -> float:
    return float(np.prod([x.shape[a] for a in _reduce_axes(x)]))


def _stats(x, stat_dtype):
    """Batch mean and biased variance per channel, accumulated in the stats
    dtype. The square stays in the ACTIVATION dtype: on the bf16 path the
    fused reduce then reads bf16 end-to-end (measured 84 vs 72 GB/s on the
    [128,56,56,256] ResNet shape) and the f32 accumulator absorbs the
    per-element mantissa loss of the bf16 square."""
    axes = _reduce_axes(x)
    n = _n_elements(x)
    mean = jnp.sum(x, axis=axes, dtype=stat_dtype) / n
    s2 = jnp.sum(jnp.square(x), axis=axes, dtype=stat_dtype)
    var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)
    return mean, var


def _normalize(x, gamma, beta, mean, var, eps):
    inv = jax.lax.rsqrt(var + eps)
    scale = (gamma * inv).astype(x.dtype)
    shift = (beta - gamma * mean * inv).astype(x.dtype)
    return x * scale + shift


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_apply(x, gamma, beta, eps):
    """Normalized output only — the differentiated part of training BN."""
    mean, var = _stats(x, gamma.dtype)
    return _normalize(x, gamma, beta, mean, var, eps)


def _vjp_fwd(x, gamma, beta, eps):
    mean, var = _stats(x, gamma.dtype)
    y = _normalize(x, gamma, beta, mean, var, eps)
    return y, (x, gamma, mean, var)


def _vjp_bwd(eps, res, dy):
    x, gamma, mean, var = res
    axes = _reduce_axes(x)
    n = _n_elements(x)
    stat_dtype = gamma.dtype
    inv = jax.lax.rsqrt(var + eps)
    m_b = mean.astype(x.dtype)
    xhat = (x - m_b) * inv.astype(x.dtype)
    dbeta = jnp.sum(dy, axis=axes, dtype=stat_dtype)
    dgamma = jnp.sum((dy * xhat).astype(stat_dtype), axis=axes,
                     dtype=stat_dtype)
    dx = (gamma * inv).astype(x.dtype) * (
        dy
        - (dbeta / n).astype(x.dtype)
        - xhat * (dgamma / n).astype(x.dtype))
    return dx, dgamma, dbeta


_bn_apply.defvjp(_vjp_fwd, _vjp_bwd)


def batch_norm_train(x, gamma, beta, eps):
    """Training-mode BN. Returns (y, batch_mean, batch_var).

    gamma/beta must be in the stats dtype (float32, or float64 under the f64
    policy); x may be bf16/f32/f64. mean/var come back in the stats dtype for
    the running-average update; they are aux state (not differentiated) and
    their computation CSEs with the forward's internal stats under jit.
    """
    y = _bn_apply(x, gamma, beta, eps)
    mean, var = _stats(x, gamma.dtype)
    return y, mean, var


def batch_norm_inference(x, gamma, beta, mean, var, eps):
    """Inference-mode BN from running stats (pure elementwise; XLA fuses it
    into the preceding conv)."""
    inv = jax.lax.rsqrt(var + eps)
    scale = (gamma * inv).astype(x.dtype)
    shift = (beta - gamma * mean * inv).astype(x.dtype)
    return x * scale + shift
