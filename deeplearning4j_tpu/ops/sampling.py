"""On-device token sampling for the fused decode loop (pure XLA).

One documented sampling semantics, shared with the HOST-side
``models.transformer.sample_token`` so the ticked scheduler path, the
full-cache oracle (``generate``) and the fused on-device loop CANNOT
drift apart (the seeded host-vs-device parity suite in
``tests/test_fused_decode.py`` pins it):

1. **temperature** ``T > 0``: reweight ``w ∝ p^(1/T)`` (equivalently
   softmax of ``log p / T``); ``T <= 0`` is greedy argmax of the RAW
   distribution (no filtering — ties break toward the lower token id on
   both host and device).
2. **top-k** (``top_k > 0``): keep the ``top_k`` highest-weight tokens
   — ties broken toward the lower token id via a stable descending sort
   — zero the rest, renormalize.
3. **top-p** (``0 < top_p < 1``): over the top-k-renormalized weights in
   descending order, keep the minimal prefix whose cumulative mass
   reaches ``top_p`` (a token is kept iff the mass BEFORE it is
   ``< top_p``, so at least one survives), zero the rest, renormalize.
4. **draw**: inverse-CDF over token ids in ASCENDING id order — the
   sampled token is the smallest id whose cumulative weight exceeds
   ``u·total`` (scaling by the total makes the draw robust to the
   cumsum not closing exactly at 1.0 in floating point).

The uniforms ``u`` are an ARGUMENT, not generated here: the serving
scheduler draws them host-side from each request's seeded
``numpy.random.Generator`` (N per lane per fused block), which keeps
per-request reproducibility independent of batch composition AND makes
host/device parity directly testable — feed both the same ``u``.

Everything is vectorized over lanes with PER-LANE ``temperature`` /
``top_k`` / ``top_p`` arrays, so one fused trace serves heterogeneous
sampling configs without retracing (greedy lanes ride the same dispatch
as sampled ones; the ``where`` on temperature picks the branch).
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["filtered_probs", "inverse_cdf", "sample_tokens"]


def filtered_probs(probs, temperature, top_k, top_p):
    """Temperature/top-k/top-p filtered, renormalized distribution.

    probs: ``[S, V]`` softmax rows; temperature/top_k/top_p: ``[S]``
    per-lane (``top_k <= 0`` = no k-filter, ``top_p <= 0`` or ``>= 1`` =
    no p-filter; ``temperature <= 0`` lanes are reweighted at T=1 — their
    callers take the greedy branch and never read this). Returns
    ``[S, V]`` float32 summing to ~1 per lane.
    """
    p = probs.astype(jnp.float32)
    v = p.shape[-1]
    t = jnp.where(temperature > 0, temperature, 1.0).astype(jnp.float32)
    logits = jnp.log(jnp.maximum(p, 1e-30)) / t[:, None]
    logits = logits - jnp.max(logits, axis=-1, keepdims=True)
    w = jnp.exp(logits)
    # descending stable order: rank r of a token = how many tokens beat
    # it (ties toward the lower id — jax sorts are stable)
    order = jnp.argsort(-w, axis=-1)                  # [S, V] ids, desc
    ranks = jnp.argsort(order, axis=-1)               # [S, V] rank per id
    k = jnp.where(top_k > 0, top_k, v).astype(jnp.int32)
    w = jnp.where(ranks < k[:, None], w, 0.0)
    w = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-30)
    # nucleus over the k-filtered dist: keep while the mass BEFORE the
    # token is < top_p (position 0 always kept)
    w_desc = jnp.take_along_axis(w, order, axis=-1)
    before = jnp.cumsum(w_desc, axis=-1) - w_desc
    tp = jnp.where((top_p > 0) & (top_p < 1), top_p, 1.0)
    tp = tp.astype(jnp.float32)
    keep_desc = before < tp[:, None]
    keep = jnp.take_along_axis(keep_desc, ranks, axis=-1)
    w = jnp.where(keep, w, 0.0)
    return w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-30)


def inverse_cdf(weights, u):
    """Inverse-CDF draw in ascending token-id order.

    weights: ``[S, V]`` nonnegative (need not be normalized — ``u`` is
    scaled by each row's total); u: ``[S]`` in [0, 1). Returns ``[S]``
    int32: the smallest id whose cumulative weight exceeds ``u·total``.
    When ``u·total`` reaches the top of the CDF in floating point (a
    host-float64 uniform within 2⁻²⁶ of 1 rounds to 1.0f), the draw
    falls back to the LAST positive-weight id — never a filtered-out
    token, which a bare argmax-over-all-False would return (id 0).
    """
    w = weights.astype(jnp.float32)
    c = jnp.cumsum(w, axis=-1)
    gt = c > (u.astype(jnp.float32) * c[:, -1])[:, None]
    v = w.shape[-1]
    last_pos = (v - 1) - jnp.argmax((w > 0)[:, ::-1], axis=-1)
    return jnp.where(jnp.any(gt, axis=-1), jnp.argmax(gt, axis=-1),
                     last_pos).astype(jnp.int32)


def sample_tokens(probs, temperature, top_k, top_p, u):
    """Per-lane next-token choice (the device twin of the host
    ``models.transformer.sample_token``): greedy argmax where
    ``temperature <= 0``, else inverse-CDF at ``u`` over the filtered
    distribution. probs ``[S, V]``, everything else ``[S]`` → ``[S]``
    int32."""
    greedy = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    sampled = inverse_cdf(filtered_probs(probs, temperature, top_k, top_p),
                          u)
    return jnp.where(temperature > 0, sampled, greedy)
