"""Common elementwise/random ops."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dropout_mask(rng, shape, rate: float, dtype=jnp.float32):
    """Inverted-dropout mask: keep with prob (1-rate), scale kept by 1/(1-rate).

    ``rate`` is the probability of dropping (Keras/modern convention; the
    reference's util/Dropout.java applies ND4J DropOutInverted — same inverted
    scaling, so train/test scaling semantics match).
    """
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, p=keep, shape=shape)
    return mask.astype(dtype) / keep


def apply_dropout(rng, x, rate: float, train: bool):
    if not train or rate <= 0.0 or rng is None:
        return x
    return x * dropout_mask(rng, x.shape, rate, x.dtype)
