"""Attention ops: fused single-device attention + ring attention (context
parallelism over the ICI mesh).

The reference has NO attention/sequence-parallel machinery (LSTM era — see
SURVEY §2.9): this is the long-context north-star extension. Design follows
the public ring-attention recipe (blockwise online-softmax accumulation while
K/V blocks rotate around the `seq` mesh axis via ``ppermute``), so sequence
length scales with the number of chips while every matmul stays MXU-shaped.

Shapes: q/k/v are [batch, time, heads, head_dim] ("BTHD").
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def dot_product_attention(q, k, v, *, causal: bool = False, mask=None,
                          scale: Optional[float] = None):
    """Standard softmax attention, single program. [b,t,h,d] → [b,t,h,d].

    mask: optional [b, t_kv] key-validity mask (1=attend).

    Calls route to the Pallas flash kernel (``ops.flash_attention``,
    key masks included) automatically at t ≥ 4096 on TPU — forward AND
    blockwise backward, ≥2× measured (PERF.md).
    ``DL4JTPU_FLASH_ATTENTION=1`` forces the kernel at any length, ``0``
    forces this XLA path."""
    from .flash_attention import flash_attention, flash_available
    if q.ndim == 4 and q.shape == k.shape == v.shape \
            and flash_available(q.shape, mask):
        return flash_attention(q, k, v, causal, scale, mask=mask)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        causal_mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(causal_mask[None, None], logits, -jnp.inf)
    if mask is not None:
        logits = jnp.where(mask[:, None, None, :] > 0, logits, -jnp.inf)
    # manual stable softmax so a query with NO attendable keys (all -inf —
    # e.g. leading padded step under a causal mask) outputs 0, not NaN;
    # same guard the ring path's _block_attend applies
    m = jnp.max(logits, axis=-1, keepdims=True)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.where(jnp.isneginf(logits), 0.0, jnp.exp(logits - m_safe))
    weights = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def _block_attend(q, k, v, m_prev, num_prev, den_prev, *, scale,
                  q_offset, k_offset, causal, key_mask=None):
    """One K/V block of online-softmax accumulation (flash-style).

    m/num/den carry the running max, weighted-value numerator, and
    normalizer. q_offset/k_offset are global time offsets of the local q
    block and current k block (for causal masking across ring hops).
    key_mask: optional [b, tk] validity of THIS k block's keys."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale   # [b,h,tq,tk]
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qi = q_offset + jnp.arange(tq)
        ki = k_offset + jnp.arange(tk)
        allow = qi[:, None] >= ki[None, :]
        logits = jnp.where(allow[None, None], logits, -jnp.inf)
    if key_mask is not None:
        logits = jnp.where(key_mask[:, None, None, :] > 0, logits,
                           -jnp.inf)
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1))   # [b,h,tq]
    # guard: rows with no allowed keys yet keep -inf max → exp(0)=1 issues;
    # use where to keep them at zero contribution
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(logits - m_safe[..., None])                 # [b,h,tq,tk]
    p = jnp.where(jnp.isneginf(logits), 0.0, p)
    correction = jnp.where(jnp.isneginf(m_prev), 0.0,
                           jnp.exp(m_prev - m_safe))
    num = (num_prev * correction[..., None]
           + jnp.einsum("bhqk,bkhd->bhqd", p, v))
    den = den_prev * correction + jnp.sum(p, axis=-1)
    return m_new, num, den


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None, mask=None):
    """Ring attention INSIDE a shard_map over `axis_name`.

    Each device holds a [b, t_local, h, d] shard of q/k/v (the global
    sequence is split over the mesh axis). K/V shards rotate around the ring
    with ``ppermute`` while each device accumulates its local queries'
    attention online — full-sequence attention without ever materializing
    the [t, t] matrix or gathering the sequence.

    ``mask``: optional [b, t_local] key-validity shard (1=attend) — it
    rotates around the ring WITH its K/V shard, so padded keys anywhere in
    the global sequence are excluded; fully-masked query rows output 0
    (same semantics as ``dot_product_attention``).
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    t_local = q.shape[1]
    b, _, h, _ = q.shape

    q32 = q.astype(jnp.float32)
    # derive accumulators from q so they carry the same varying-across-mesh
    # type as the loop body's outputs (shard_map vma consistency)
    base = jnp.moveaxis(q32[..., 0], 1, 2)                  # [b,h,t_local]
    m = jnp.full_like(base, -jnp.inf)
    num = jnp.zeros_like(jnp.moveaxis(q32, 1, 2))           # [b,h,t_local,d]
    den = jnp.zeros_like(base)
    q_offset = idx * t_local

    perm = [(i, (i + 1) % n) for i in range(n)]
    # mask is a trace-time condition: the unmasked ring keeps its original
    # 5-tuple carry (no extra ppermute riding the hot path)
    extra = () if mask is None else (mask.astype(jnp.float32),)

    def body(i, carry):
        m, num, den, k_blk, v_blk, *mk = carry
        # the block currently held came from device (idx - i) mod n
        src = jnp.mod(idx - i, n)
        k_offset = src * t_local
        m, num, den = _block_attend(
            q32, k_blk.astype(jnp.float32), v_blk.astype(jnp.float32),
            m, num, den, scale=scale, q_offset=q_offset,
            k_offset=k_offset, causal=causal,
            key_mask=mk[0] if mk else None)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        mk = tuple(jax.lax.ppermute(x, axis_name, perm) for x in mk)
        return (m, num, den, k_blk, v_blk, *mk)

    m, num, den, *_ = jax.lax.fori_loop(
        0, n, body, (m, num, den, k, v, *extra))
    out = num / jnp.maximum(den[..., None], 1e-30)          # [b,h,tq,d]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [b,tq,h,d]


def make_ring_attention(mesh, axis_name: str = "seq", *,
                        causal: bool = False, batch_axis: Optional[str] = None,
                        with_mask: bool = False):
    """shard_map-wrapped ring attention: takes GLOBAL [b, t, h, d] arrays
    sharded (or shardable) over `axis_name` on the time axis, returns the
    global attention output with the same sharding.

    ``batch_axis``: optional mesh axis the BATCH dim is data-parallel over
    (2-D dp x sp meshes) — each dp slice runs its own independent ring over
    ``axis_name``; without it a dp-sharded batch would be gathered.

    ``with_mask=True`` returns ``fn(q, k, v, mask)`` where mask is the
    GLOBAL [b, t] key-validity array (sharded over ``axis_name`` like the
    time axis); mask shards rotate around the ring with their K/V."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axis, axis_name, None, None)
    mspec = P(batch_axis, axis_name)

    if with_mask:
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(spec, spec, spec, mspec),
                           out_specs=spec)
        def fn(q, k, v, mask):
            return ring_attention(q, k, v, axis_name=axis_name,
                                  causal=causal, mask=mask)
        return fn

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal)

    return fn


# --------------------------------------------------------------------------
# sequence-sharding context: how DSL layers discover an active seq mesh
# --------------------------------------------------------------------------

_SEQ_SHARDING: Optional[tuple] = None


class sequence_sharding:
    """Trace-time context that routes ``SelfAttentionLayer`` (and any other
    time-mixing op that opts in) to ring attention over ``seq_axis``.

    Usage — activate around the *trace* of a step function::

        with sequence_sharding(mesh, "seq", batch_axis="dp"):
            loss = jax.jit(step)(params, x, y)   # first call traces here

    The context is read at trace time (like the flash-attention flag): the
    chosen route is baked into the compiled program, which is exactly what
    a sharded trainer wants — its step is always ring-routed, while the
    same model object used outside the context keeps its single-device
    program.
    """

    def __init__(self, mesh, seq_axis: str = "seq",
                 batch_axis: Optional[str] = None):
        self.value = (mesh, seq_axis, batch_axis)

    def __enter__(self):
        global _SEQ_SHARDING
        self._prev = _SEQ_SHARDING
        _SEQ_SHARDING = self.value
        return self

    def __exit__(self, *exc):
        global _SEQ_SHARDING
        _SEQ_SHARDING = self._prev
        return False


def active_sequence_sharding() -> Optional[tuple]:
    """(mesh, seq_axis, batch_axis) if a sequence_sharding context is
    active, else None."""
    return _SEQ_SHARDING
