"""Attention ops: fused single-device attention + ring attention (context
parallelism over the ICI mesh).

The reference has NO attention/sequence-parallel machinery (LSTM era — see
SURVEY §2.9): this is the long-context north-star extension. Design follows
the public ring-attention recipe (blockwise online-softmax accumulation while
K/V blocks rotate around the `seq` mesh axis via ``ppermute``), so sequence
length scales with the number of chips while every matmul stays MXU-shaped.

Shapes: q/k/v are [batch, time, heads, head_dim] ("BTHD").
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def dot_product_attention(q, k, v, *, causal: bool = False, mask=None,
                          scale: Optional[float] = None):
    """Standard softmax attention, single program. [b,t,h,d] → [b,t,h,d].

    mask: optional [b, t_kv] key-validity mask (1=attend).

    Calls route to the Pallas flash kernel (``ops.flash_attention``,
    key masks included) automatically at t ≥ 4096 on TPU — forward AND
    blockwise backward, ≥2× measured (PERF.md).
    ``DL4JTPU_FLASH_ATTENTION=1`` forces the kernel at any length, ``0``
    forces this XLA path."""
    from .flash_attention import flash_attention, flash_available
    if q.ndim == 4 and q.shape == k.shape == v.shape \
            and flash_available(q.shape, mask):
        return flash_attention(q, k, v, causal, scale, mask=mask)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        causal_mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(causal_mask[None, None], logits, -jnp.inf)
    if mask is not None:
        logits = jnp.where(mask[:, None, None, :] > 0, logits, -jnp.inf)
    # manual stable softmax so a query with NO attendable keys (all -inf —
    # e.g. leading padded step under a causal mask) outputs 0, not NaN;
    # same guard the ring path's _block_attend applies
    m = jnp.max(logits, axis=-1, keepdims=True)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.where(jnp.isneginf(logits), 0.0, jnp.exp(logits - m_safe))
    weights = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def _block_attend(q, k, v, m_prev, num_prev, den_prev, *, scale,
                  q_offset, k_offset, causal, key_mask=None):
    """One K/V block of online-softmax accumulation (flash-style).

    m/num/den carry the running max, weighted-value numerator, and
    normalizer. q_offset/k_offset are global time offsets of the local q
    block and current k block (for causal masking across ring hops).
    key_mask: optional [b, tk] validity of THIS k block's keys."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale   # [b,h,tq,tk]
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qi = q_offset + jnp.arange(tq)
        ki = k_offset + jnp.arange(tk)
        allow = qi[:, None] >= ki[None, :]
        logits = jnp.where(allow[None, None], logits, -jnp.inf)
    if key_mask is not None:
        logits = jnp.where(key_mask[:, None, None, :] > 0, logits,
                           -jnp.inf)
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1))   # [b,h,tq]
    # guard: rows with no allowed keys yet keep -inf max → exp(0)=1 issues;
    # use where to keep them at zero contribution
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(logits - m_safe[..., None])                 # [b,h,tq,tk]
    p = jnp.where(jnp.isneginf(logits), 0.0, p)
    correction = jnp.where(jnp.isneginf(m_prev), 0.0,
                           jnp.exp(m_prev - m_safe))
    num = (num_prev * correction[..., None]
           + jnp.einsum("bhqk,bkhd->bhqd", p, v))
    den = den_prev * correction + jnp.sum(p, axis=-1)
    return m_new, num, den


def ring_flash_available(t_local: int) -> bool:
    """Should ring attention run its hops through the Pallas flash kernel?

    Same trace-time contract as ``flash_attention.flash_available``:
    ``DL4JTPU_FLASH_ATTENTION=1`` forces the kernel-in-ring path at any
    length (interpret-mode off-TPU, so CPU test meshes exercise the real
    carry/VJP protocol), ``0`` forces the JAX-level online-softmax block
    (the parity oracle), unset = auto — on for per-device shards of
    t_local ≥ 1024 on the TPU backend. Non-divisible t_local is handled
    by the flash path itself (end-of-shard padding under a key mask), so
    divisibility never forces the oracle."""
    import os
    flag = os.environ.get("DL4JTPU_FLASH_ATTENTION", "auto")
    if flag == "0":
        return False
    if flag == "1":
        return True
    return t_local >= 1024 and jax.devices()[0].platform == "tpu"


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None, mask=None,
                   impl: Optional[str] = None):
    """Ring attention INSIDE a shard_map over `axis_name`.

    Each device holds a [b, t_local, h, d] shard of q/k/v (the global
    sequence is split over the mesh axis). K/V shards rotate around the ring
    with ``ppermute`` while each device accumulates its local queries'
    attention online — full-sequence attention without ever materializing
    the [t, t] matrix or gathering the sequence.

    ``mask``: optional [b, t_local] key-validity shard (1=attend) — it
    rotates around the ring WITH its K/V shard, so padded keys anywhere in
    the global sequence are excluded; fully-masked query rows output 0
    (same semantics as ``dot_product_attention``).

    ``impl``: ``"flash"`` runs every hop through the Pallas flash kernel
    (forward AND backward — see ``_ring_flash_attention``), ``"jax"``
    keeps the JAX-level online-softmax block below (the parity oracle),
    ``None`` routes via :func:`ring_flash_available` at trace time.
    """
    if impl is None:
        impl = "flash" if ring_flash_available(q.shape[1]) else "jax"
    if impl == "flash":
        return _ring_flash_attention(q, k, v, mask, axis_name=axis_name,
                                     causal=causal, scale=scale)
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    t_local = q.shape[1]
    b, _, h, _ = q.shape

    q32 = q.astype(jnp.float32)
    # derive accumulators from q so they carry the same varying-across-mesh
    # type as the loop body's outputs (shard_map vma consistency)
    base = jnp.moveaxis(q32[..., 0], 1, 2)                  # [b,h,t_local]
    m = jnp.full_like(base, -jnp.inf)
    num = jnp.zeros_like(jnp.moveaxis(q32, 1, 2))           # [b,h,t_local,d]
    den = jnp.zeros_like(base)
    q_offset = idx * t_local

    perm = [(i, (i + 1) % n) for i in range(n)]
    # mask is a trace-time condition: the unmasked ring keeps its original
    # 5-tuple carry (no extra ppermute riding the hot path)
    extra = () if mask is None else (mask.astype(jnp.float32),)

    def body(i, carry):
        m, num, den, k_blk, v_blk, *mk = carry
        # the block currently held came from device (idx - i) mod n
        src = jnp.mod(idx - i, n)
        k_offset = src * t_local
        m, num, den = _block_attend(
            q32, k_blk.astype(jnp.float32), v_blk.astype(jnp.float32),
            m, num, den, scale=scale, q_offset=q_offset,
            k_offset=k_offset, causal=causal,
            key_mask=mk[0] if mk else None)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        mk = tuple(jax.lax.ppermute(x, axis_name, perm) for x in mk)
        return (m, num, den, k_blk, v_blk, *mk)

    m, num, den, *_ = jax.lax.fori_loop(
        0, n, body, (m, num, den, k, v, *extra))
    out = num / jnp.maximum(den[..., None], 1e-30)          # [b,h,tq,d]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [b,tq,h,d]


# --------------------------------------------------------------------------
# ring-flash: every hop through the Pallas flash kernel, fwd AND bwd
# --------------------------------------------------------------------------
#
# Protocol (see flash_attention.flash_attention_block): each device keeps an
# online-softmax carry (m, l, o) for its LOCAL queries; every visiting K/V
# shard is one flash-kernel call folded into the carry. Cross-hop causal
# masking needs no dynamic offsets inside the kernel — a hop pair
# (q from device ``idx``, k/v born on device ``src``) is entirely
# pre-diagonal (src < idx → plain non-causal kernel), on the diagonal
# (src == idx → causal kernel), or entirely post-diagonal (src > idx →
# skipped, no kernel at all), selected with ``lax.switch`` on the traced
# hop index. The backward is a SECOND ring over the same ``ppermute``
# permutation: dq accumulates locally from the per-hop flash backward
# kernels (P recomputed from the saved full-sequence lse), while dk/dv
# accumulators travel WITH their K/V shard and arrive home after the full
# rotation.


def _ring_hop_branches(q32, scale, block_q, interpret):
    """(full, diag, skip) forward-hop branches for ``lax.switch``."""
    from .flash_attention import flash_attention_block

    def full(c, kb, vb, mb):
        return flash_attention_block(q32, kb, vb, c, causal=False,
                                     scale=scale, mask=mb, block_q=block_q,
                                     interpret=interpret)

    def diag(c, kb, vb, mb):
        return flash_attention_block(q32, kb, vb, c, causal=True,
                                     scale=scale, mask=mb, block_q=block_q,
                                     interpret=interpret)

    def skip(c, kb, vb, mb):
        return c

    return full, diag, skip


def _ring_flash_fwd_impl(q, k, v, mask, axis_name, causal, scale, block_q,
                         interpret):
    from .flash_attention import flash_carry_finalize, flash_carry_init
    n = jax.lax.psum(1, axis_name)
    # axis_index only when the hop trichotomy needs it: a dangling
    # partition-id in the non-causal program trips the CPU SPMD
    # partitioner (PartitionId outside a recognized manual region)
    idx = jax.lax.axis_index(axis_name) if causal else 0
    q32 = q.astype(jnp.float32)
    full, diag, skip = _ring_hop_branches(q32, scale, block_q, interpret)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(i, st):
        c, kb, vb, mb = st
        src = jnp.mod(idx - i, n)
        if causal:
            branch = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
            c = jax.lax.switch(branch, (full, diag, skip), c, kb, vb, mb)
        else:
            c = full(c, kb, vb, mb)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        mb = jax.lax.ppermute(mb, axis_name, perm)
        return c, kb, vb, mb

    carry, *_ = jax.lax.fori_loop(
        0, n, body, (flash_carry_init(q32), k, v, mask))
    out32, lse = flash_carry_finalize(carry)
    return out32, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _ring_flash_core(q, k, v, mask, axis_name, causal, scale, block_q,
                     interpret):
    out32, _ = _ring_flash_fwd_impl(q, k, v, mask, axis_name, causal,
                                    scale, block_q, interpret)
    return out32.astype(q.dtype)


def _ring_flash_fwd_rule(q, k, v, mask, axis_name, causal, scale, block_q,
                         interpret):
    out32, lse = _ring_flash_fwd_impl(q, k, v, mask, axis_name, causal,
                                      scale, block_q, interpret)
    return out32.astype(q.dtype), (q, k, v, mask, out32, lse)


def _ring_flash_bwd_rule(axis_name, causal, scale, block_q, interpret,
                         res, g):
    from .flash_attention import flash_attention_bwd_block
    q, k, v, mask, out32, lse = res
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name) if causal else 0  # see fwd note
    q32 = q.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop(kb, vb, mb, diag):
        return flash_attention_bwd_block(
            q32, kb.astype(jnp.float32), vb.astype(jnp.float32), out32,
            lse, g32, causal=diag, scale=scale, mask=mb, block_q=block_q,
            interpret=interpret)

    def full(kb, vb, mb):
        return hop(kb, vb, mb, False)

    def diag(kb, vb, mb):
        return hop(kb, vb, mb, True)

    def skip(kb, vb, mb):
        z = jnp.zeros_like(q32)
        return z, jnp.zeros_like(z), jnp.zeros_like(z)

    def body(i, st):
        dq, dk, dv, kb, vb, mb = st
        src = jnp.mod(idx - i, n)
        if causal:
            branch = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
            dq_h, dk_h, dv_h = jax.lax.switch(
                branch, (full, diag, skip), kb, vb, mb)
        else:
            dq_h, dk_h, dv_h = full(kb, vb, mb)
        dq = dq + dq_h.astype(jnp.float32)
        dk = dk + dk_h.astype(jnp.float32)
        dv = dv + dv_h.astype(jnp.float32)
        # dk/dv accumulators travel WITH their shard: after the full
        # rotation each lands back on its home device, complete
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        mb = jax.lax.ppermute(mb, axis_name, perm)
        dk = jax.lax.ppermute(dk, axis_name, perm)
        dv = jax.lax.ppermute(dv, axis_name, perm)
        return dq, dk, dv, kb, vb, mb

    zeros = jnp.zeros_like(q32)
    dq, dk, dv, *_ = jax.lax.fori_loop(
        0, n, body, (zeros, jnp.zeros_like(zeros), jnp.zeros_like(zeros),
                     k, v, mask))
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            jnp.zeros_like(mask))


_ring_flash_core.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def _ring_flash_attention(q, k, v, mask, *, axis_name: str, causal: bool,
                          scale: Optional[float],
                          block_q: Optional[int] = None):
    """Flash-kernel ring attention on the LOCAL shards (inside shard_map).

    Handles ragged shards here, outside the custom VJP: t_local that does
    not divide the flash tile is padded at the END of every shard (keys
    masked out, query rows sliced off after), which preserves global
    causal order because the hop trichotomy (pre/diagonal/post) only
    compares shard indices. ``interpret`` is resolved at trace time so CPU
    meshes run the kernels in interpret mode."""
    t_local = q.shape[1]
    d = q.shape[-1]
    scale = float(scale) if scale is not None else 1.0 / float(d) ** 0.5
    interpret = jax.devices()[0].platform != "tpu"
    bq = block_q or (128 if t_local >= 128 else -(-t_local // 8) * 8)
    pad = (-t_local) % bq
    if mask is None:
        mask = jnp.ones((q.shape[0], t_local), jnp.float32)
    mask = jnp.asarray(mask, jnp.float32)
    if pad:
        widths = ((0, 0), (0, pad), (0, 0), (0, 0))
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    out = _ring_flash_core(q, k, v, mask, axis_name, causal, scale, bq,
                           interpret)
    return out[:, :t_local] if pad else out


def make_ring_attention(mesh, axis_name: str = "seq", *,
                        causal: bool = False, batch_axis: Optional[str] = None,
                        with_mask: bool = False):
    """shard_map-wrapped ring attention: takes GLOBAL [b, t, h, d] arrays
    sharded (or shardable) over `axis_name` on the time axis, returns the
    global attention output with the same sharding.

    ``batch_axis``: optional mesh axis the BATCH dim is data-parallel over
    (2-D dp x sp meshes) — each dp slice runs its own independent ring over
    ``axis_name``; without it a dp-sharded batch would be gathered.

    ``with_mask=True`` returns ``fn(q, k, v, mask)`` where mask is the
    GLOBAL [b, t] key-validity array (sharded over ``axis_name`` like the
    time axis); mask shards rotate around the ring with their K/V."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axis, axis_name, None, None)
    mspec = P(batch_axis, axis_name)
    # check_rep=False: the flash route's pallas_call has no shard_map
    # replication rule (the ring touches no replicated operands anyway —
    # everything it moves is axis-sharded)
    smap = functools.partial(shard_map, mesh=mesh, check_rep=False)

    if with_mask:
        @functools.partial(smap, in_specs=(spec, spec, spec, mspec),
                           out_specs=spec)
        def fn(q, k, v, mask):
            return ring_attention(q, k, v, axis_name=axis_name,
                                  causal=causal, mask=mask)
        return fn

    @functools.partial(smap, in_specs=(spec, spec, spec), out_specs=spec)
    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal)

    return fn


# --------------------------------------------------------------------------
# sequence-sharding context: how DSL layers discover an active seq mesh
# --------------------------------------------------------------------------

_SEQ_SHARDING: Optional[tuple] = None


class sequence_sharding:
    """Trace-time context that routes ``SelfAttentionLayer`` (and any other
    time-mixing op that opts in) to ring attention over ``seq_axis``.

    Usage — activate around the *trace* of a step function::

        with sequence_sharding(mesh, "seq", batch_axis="dp"):
            loss = jax.jit(step)(params, x, y)   # first call traces here

    The context is read at trace time (like the flash-attention flag): the
    chosen route is baked into the compiled program, which is exactly what
    a sharded trainer wants — its step is always ring-routed, while the
    same model object used outside the context keeps its single-device
    program.
    """

    def __init__(self, mesh, seq_axis: str = "seq",
                 batch_axis: Optional[str] = None):
        self.value = (mesh, seq_axis, batch_axis)

    def __enter__(self):
        global _SEQ_SHARDING
        self._prev = _SEQ_SHARDING
        _SEQ_SHARDING = self.value
        return self

    def __exit__(self, *exc):
        global _SEQ_SHARDING
        _SEQ_SHARDING = self._prev
        return False


def active_sequence_sharding() -> Optional[tuple]:
    """(mesh, seq_axis, batch_axis) if a sequence_sharding context is
    active, else None."""
    return _SEQ_SHARDING
