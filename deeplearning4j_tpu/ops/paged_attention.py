"""Paged KV-cache attention primitives (gather/scatter, pure XLA).

vLLM-style block cache (PAPERS: PagedAttention/SOSP'23) for the
continuous-batching decode path: per-layer K/V live in preallocated
``[num_pages, page_size, heads, head_dim]`` block pools; each sequence
owns an ordered *page table* of physical page ids. A decode step scatters
the new tokens' K/V into the pools at (page, offset) and gathers each
sequence's pages back into a contiguous ``[window, heads, head_dim]``
view — the gathered view IS the dense streaming cache reassembled, so the
attention math here mirrors ``SelfAttentionLayer._apply_streaming`` term
for term and greedy decode through the arena is bit-exact against the
dense full-cache path for sequences within the window (the parity suite
in ``tests/test_decode.py`` pins it; past the window the paths evict at
different granularity — a page here, a token there — and diverge by
design).

Layout conventions (shared with ``serving/kv_cache.py`` and
``serving/decode.py``):

- page tables are ``[lanes, pages_per_seq]`` int32 of PHYSICAL page ids;
  unallocated entries hold the SENTINEL ``num_pages`` (one past the pool)
  — gathers fill zeros there, scatters drop.
- write positions are VIEW-relative slots ``global_pos - base`` where
  ``base`` is the number of evicted positions (pages_evicted ×
  page_size); ``-1`` marks padded lanes/tokens (dropped).
- sliding-window overflow is PAGE EVICTION, done host-side by the engine
  (the page table shifts, ``base`` advances) — positions stay global, and
  the causal mask below automatically hides a recycled page's stale tail.

Everything is plain gather/scatter + einsum: XLA lowers it well on both
the CPU test mesh and TPU, and there is no dynamic shape anywhere — the
scheduler can admit/retire sequences every step without retracing.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["paged_write", "paged_gather", "paged_attention"]


def paged_write(pool, new, page_table, write_slots):
    """Scatter new K (or V) rows into the block pool.

    pool: ``[num_pages, page_size, h, d]`` — or, int8-quantized, a
    ``(q_int8, scales)`` tuple (see :func:`_paged_write_q8`); new:
    ``[S, t_new, h, d]``; page_table: ``[S, P]`` physical page ids;
    write_slots: ``[S, t_new]`` view-relative slot per token (``-1`` =
    padded, dropped). Returns the updated pool (same structure as the
    input). Out-of-range/sentinel targets are dropped, so padded lanes
    can never corrupt a live page.
    """
    if isinstance(pool, tuple):
        return _paged_write_q8(pool, new, page_table, write_slots)
    num_pages, page_size = pool.shape[0], pool.shape[1]
    p_idx = jnp.clip(write_slots // page_size, 0, page_table.shape[1] - 1)
    off = write_slots % page_size
    phys = jnp.take_along_axis(page_table, p_idx, axis=1)
    # padded tokens (slot < 0) and sentinel table entries both land out of
    # bounds → mode="drop" discards the write
    phys = jnp.where(write_slots >= 0, phys, num_pages)
    return pool.at[phys, off].set(new.astype(pool.dtype), mode="drop")


def _paged_write_q8(pool, new, page_table, write_slots):
    """int8 write path: ``pool = (q, scales)`` with ``q`` the
    ``[num_pages, page_size, h, d]`` int8 codes and ``scales`` the
    per-(page, head) ``[num_pages, h]`` f32 quantization step.

    Scales are MONOTONE per page: a write first folds the new rows'
    amax into ``new_scale = max(old_scale, amax/127)``, rescales the
    touched pages' existing codes by ``old/new`` (duplicate page ids
    scatter identical values, so the update is idempotent), then writes
    the new rows quantized at the new scale. Monotonicity keeps already
    written tokens valid without tracking per-row scales; the bounded
    requantization drift it costs is covered by the int8 quality gate
    (logit max-err + greedy divergence, see PERF.md).
    """
    q, scales = pool
    num_pages, page_size = q.shape[0], q.shape[1]
    h = q.shape[2]
    p_idx = jnp.clip(write_slots // page_size, 0, page_table.shape[1] - 1)
    off = write_slots % page_size
    phys = jnp.take_along_axis(page_table, p_idx, axis=1)
    phys = jnp.where(write_slots >= 0, phys, num_pages)       # [S, t]
    newf = new.astype(jnp.float32)
    # 1) fold the new rows' amax into the touched pages' scales
    amax_tok = jnp.max(jnp.abs(newf), axis=-1)                # [S, t, h]
    flat_phys = phys.reshape(-1)
    amax_page = (jnp.zeros((num_pages, h), jnp.float32)
                 .at[flat_phys].max(amax_tok.reshape(-1, h), mode="drop"))
    new_scales = jnp.maximum(scales, amax_page / 127.0)
    # 2) rescale ONLY the touched pages' existing codes to the new step
    ratio = jnp.where(new_scales > 0, scales / new_scales, 0.0)
    pages_q = jnp.take(q, flat_phys, axis=0, mode="fill", fill_value=0)
    r = jnp.take(ratio, flat_phys, axis=0,
                 mode="fill", fill_value=0.0)[:, None, :, None]
    q = q.at[flat_phys].set(
        jnp.round(pages_q.astype(jnp.float32) * r).astype(jnp.int8),
        mode="drop")
    # 3) quantize the new rows at the new step and scatter them in
    s_tok = jnp.take(new_scales, phys, axis=0,
                     mode="fill", fill_value=0.0)              # [S, t, h]
    rows = jnp.round(newf / jnp.maximum(s_tok[..., None], 1e-30))
    rows = jnp.clip(rows, -127, 127).astype(jnp.int8)
    q = q.at[phys, off].set(rows, mode="drop")
    return (q, new_scales)


def paged_gather(pool, page_table):
    """Gather each lane's pages into a contiguous view.

    pool: ``[num_pages, page_size, h, d]`` (or the int8
    ``(q, scales)`` tuple — dequantized here, the one place reads
    happen); page_table: ``[S, P]`` → ``[S, P·page_size, h, d]``.
    Sentinel entries read as zeros (masked by the causal window in
    :func:`paged_attention` anyway).
    """
    if isinstance(pool, tuple):
        q, scales = pool
        g = jnp.take(q, page_table, axis=0, mode="fill", fill_value=0)
        sc = jnp.take(scales, page_table, axis=0,
                      mode="fill", fill_value=0.0)            # [S, P, h]
        g = g.astype(jnp.float32) * sc[:, :, None, :, None]
        s, p, page_size, h, d = g.shape
        return g.reshape(s, p * page_size, h, d)
    g = jnp.take(pool, page_table, axis=0, mode="fill", fill_value=0)
    s, p, page_size, h, d = g.shape
    return g.reshape(s, p * page_size, h, d)


def paged_attention(q, k_view, v_view, rel_pos, scale):
    """Causal attention of new queries over the gathered paged view.

    The EXACT streaming-decode softmax math from
    ``SelfAttentionLayer._apply_streaming`` (max-subtraction in f32,
    masked exp, 1e-30 denominator floor) — kept identical on purpose so
    the paged path is bit-exact against the dense cache.

    q: ``[S, t_new, h, d]`` (compute dtype); k_view/v_view:
    ``[S, W, h, d]`` (cache dtype); rel_pos: ``[S]`` view-relative
    position of each lane's FIRST new query (``global_pos - base``).
    Returns ``[S, t_new, h, d]``.
    """
    t_new = q.shape[1]
    w = k_view.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_view) * scale
    key_idx = jnp.arange(w)
    q_idx = rel_pos[:, None] + jnp.arange(t_new)[None, :]     # [S, t_new]
    allow = key_idx[None, None, :] <= q_idx[:, :, None]       # [S, t_new, W]
    logits = jnp.where(allow[:, None], logits.astype(jnp.float32),
                       -jnp.inf)
    m = jnp.max(logits, axis=-1, keepdims=True)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.where(jnp.isneginf(logits), 0.0, jnp.exp(logits - m_safe))
    weights = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    return jnp.einsum("bhqk,bkhd->bqhd", weights.astype(q.dtype), v_view)
