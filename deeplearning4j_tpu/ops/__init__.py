"""Low-level TPU ops: conv/pool lowerings, LRN, dropout, Pallas kernels.

This package is the analog of the reference's "helper seam"
(``nn/layers/convolution/ConvolutionLayer.java:66-74`` reflectively loading
CudnnConvolutionHelper): the place where layer math meets hardware. Here the
default lowering is XLA HLO (``lax.conv_general_dilated``, ``lax.reduce_window``
— already MXU-tiled by XLA:TPU); Pallas kernels slot in where the profiler
shows XLA underperforming (see ``pallas/``).
"""

from .convops import conv2d, pool2d, lrn, conv_output_size, same_pad  # noqa: F401
from .common import dropout_mask, apply_dropout  # noqa: F401
