"""Early stopping: epoch-loop driver with score calculators, termination
conditions, and model savers.

Parity: reference ``deeplearning4j-nn/.../earlystopping/`` —
``EarlyStoppingConfiguration``, ``trainer/BaseEarlyStoppingTrainer`` /
``EarlyStoppingTrainer`` / ``EarlyStoppingGraphTrainer``,
``scorecalc/DataSetLossCalculator``, ``termination/`` (MaxEpochs, MaxTime,
MaxScore, ScoreImprovement, BestScoreEpoch, InvalidScore), ``saver/``
(InMemory, LocalFile).
"""

from .config import EarlyStoppingConfiguration, EarlyStoppingResult
from .savers import InMemoryModelSaver, LocalFileModelSaver
from .scorecalc import DataSetLossCalculator, EvaluationScoreCalculator
from .termination import (
    BestScoreEpochTerminationCondition,
    InvalidScoreIterationTerminationCondition,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from .trainer import EarlyStoppingGraphTrainer, EarlyStoppingTrainer

__all__ = [
    "EarlyStoppingConfiguration", "EarlyStoppingResult",
    "EarlyStoppingTrainer", "EarlyStoppingGraphTrainer",
    "DataSetLossCalculator", "EvaluationScoreCalculator",
    "MaxEpochsTerminationCondition", "MaxTimeTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
    "BestScoreEpochTerminationCondition",
    "MaxScoreIterationTerminationCondition",
    "InvalidScoreIterationTerminationCondition",
    "InMemoryModelSaver", "LocalFileModelSaver",
]
