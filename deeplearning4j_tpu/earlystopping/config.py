"""EarlyStoppingConfiguration + result (parity: reference
``earlystopping/EarlyStoppingConfiguration.java``, ``EarlyStoppingResult.java``)."""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

from .savers import InMemoryModelSaver, ModelSaver
from .scorecalc import ScoreCalculator
from .termination import (EpochTerminationCondition,
                          IterationTerminationCondition)


@dataclasses.dataclass
class EarlyStoppingConfiguration:
    score_calculator: ScoreCalculator
    epoch_termination_conditions: List[EpochTerminationCondition] = \
        dataclasses.field(default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = \
        dataclasses.field(default_factory=list)
    model_saver: ModelSaver = dataclasses.field(default_factory=InMemoryModelSaver)
    save_last_model: bool = False
    evaluate_every_n_epochs: int = 1

    class Builder:
        def __init__(self):
            self._kw = dict(score_calculator=None,
                            epoch_termination_conditions=[],
                            iteration_termination_conditions=[],
                            model_saver=InMemoryModelSaver(),
                            save_last_model=False,
                            evaluate_every_n_epochs=1)

        def score_calculator(self, sc):
            self._kw["score_calculator"] = sc; return self

        def epoch_termination_conditions(self, *conds):
            self._kw["epoch_termination_conditions"] = list(conds); return self

        def iteration_termination_conditions(self, *conds):
            self._kw["iteration_termination_conditions"] = list(conds); return self

        def model_saver(self, saver):
            self._kw["model_saver"] = saver; return self

        def save_last_model(self, flag: bool = True):
            self._kw["save_last_model"] = bool(flag); return self

        def evaluate_every_n_epochs(self, n: int):
            self._kw["evaluate_every_n_epochs"] = int(n); return self

        def build(self) -> "EarlyStoppingConfiguration":
            if self._kw["score_calculator"] is None:
                raise ValueError("score_calculator is required")
            return EarlyStoppingConfiguration(**self._kw)

    @staticmethod
    def builder() -> "EarlyStoppingConfiguration.Builder":
        return EarlyStoppingConfiguration.Builder()


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: str           # "epoch_condition" | "iteration_condition" | "error"
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    score_vs_epoch: dict
    best_model: Any = None
