"""Model savers (parity: reference ``earlystopping/saver/`` — InMemory,
LocalFileModelSaver persisting best/latest models)."""

from __future__ import annotations

import os
from typing import Optional


class ModelSaver:
    def save_best_model(self, net, score: float) -> None:
        raise NotImplementedError

    def save_latest_model(self, net, score: float) -> None:
        raise NotImplementedError

    def get_best_model(self):
        raise NotImplementedError

    def get_latest_model(self):
        raise NotImplementedError


class InMemoryModelSaver(ModelSaver):
    """Keeps deep param copies in memory (parity: ``InMemoryModelSaver``)."""

    def __init__(self):
        self._best = None
        self._latest = None

    @staticmethod
    def _snapshot(net):
        import jax
        import copy
        return {
            "conf_json": net.conf.to_json(),
            "params": net.clone_params(),
            "state": jax.tree_util.tree_map(lambda a: a, net.state),
            "model_class": type(net).__name__,
        }

    @staticmethod
    def _restore(snap):
        if snap is None:
            return None
        if snap["model_class"] == "ComputationGraph":
            from ..nn.graph_runtime import ComputationGraph
            from ..nn.conf.graph import ComputationGraphConfiguration
            net = ComputationGraph(
                ComputationGraphConfiguration.from_json(snap["conf_json"])).init()
        else:
            from ..nn.multilayer import MultiLayerNetwork
            from ..nn.conf.multi_layer import MultiLayerConfiguration
            net = MultiLayerNetwork(
                MultiLayerConfiguration.from_json(snap["conf_json"])).init()
        net.params = snap["params"]
        net.state = snap["state"]
        return net

    def save_best_model(self, net, score: float) -> None:
        self._best = self._snapshot(net)

    def save_latest_model(self, net, score: float) -> None:
        self._latest = self._snapshot(net)

    def get_best_model(self):
        return self._restore(self._best)

    def get_latest_model(self):
        return self._restore(self._latest)


class LocalFileModelSaver(ModelSaver):
    """Writes checkpoint zips to a directory (parity: ``LocalFileModelSaver``)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    @property
    def best_path(self) -> str:
        return os.path.join(self.directory, "bestModel.zip")

    @property
    def latest_path(self) -> str:
        return os.path.join(self.directory, "latestModel.zip")

    def save_best_model(self, net, score: float) -> None:
        from ..util import save_model
        save_model(net, self.best_path)

    def save_latest_model(self, net, score: float) -> None:
        from ..util import save_model
        save_model(net, self.latest_path)

    def _load(self, path: str):
        if not os.path.exists(path):
            return None
        from ..util import load_model
        return load_model(path)

    def get_best_model(self):
        return self._load(self.best_path)

    def get_latest_model(self):
        return self._load(self.latest_path)
