"""Model savers (parity: reference ``earlystopping/saver/`` — InMemory,
LocalFileModelSaver persisting best/latest models)."""

from __future__ import annotations

import os
from typing import Optional


class ModelSaver:
    def save_best_model(self, net, score: float) -> None:
        raise NotImplementedError

    def save_latest_model(self, net, score: float) -> None:
        raise NotImplementedError

    def get_best_model(self):
        raise NotImplementedError

    def get_latest_model(self):
        raise NotImplementedError


class InMemoryModelSaver(ModelSaver):
    """Keeps deep param copies in memory (parity: ``InMemoryModelSaver``)."""

    def __init__(self):
        self._best = None
        self._latest = None

    @staticmethod
    def _snapshot(net):
        import jax
        import copy
        return {
            "conf_json": net.conf.to_json(),
            "params": net.clone_params(),
            "state": jax.tree_util.tree_map(lambda a: a, net.state),
            "model_class": type(net).__name__,
        }

    @staticmethod
    def _restore(snap):
        if snap is None:
            return None
        if snap["model_class"] == "ComputationGraph":
            from ..nn.graph_runtime import ComputationGraph
            from ..nn.conf.graph import ComputationGraphConfiguration
            net = ComputationGraph(
                ComputationGraphConfiguration.from_json(snap["conf_json"])).init()
        else:
            from ..nn.multilayer import MultiLayerNetwork
            from ..nn.conf.multi_layer import MultiLayerConfiguration
            net = MultiLayerNetwork(
                MultiLayerConfiguration.from_json(snap["conf_json"])).init()
        net.params = snap["params"]
        net.state = snap["state"]
        return net

    def save_best_model(self, net, score: float) -> None:
        self._best = self._snapshot(net)

    def save_latest_model(self, net, score: float) -> None:
        self._latest = self._snapshot(net)

    def get_best_model(self):
        return self._restore(self._best)

    def get_latest_model(self):
        return self._restore(self._latest)


class LocalFileModelSaver(ModelSaver):
    """Writes checkpoint zips to a directory (parity: ``LocalFileModelSaver``).

    Durability: each save stages through a temp file (the serializer's
    tmp+rename), is manifest-validated BEFORE it replaces the published
    name, and the previously published model rotates to ``*.prev.zip`` —
    so a crash or torn write mid-``save_best_model`` can never leave the
    best model unreadable. ``get_best_model``/``get_latest_model``
    validate on read and fall back past an invalid file to the rotated
    predecessor, the same newest-VALID-wins contract as
    ``CheckpointRecovery.latest_valid()``.
    """

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        # staging leftovers from a process killed mid-_save would
        # otherwise accumulate across crash/restart cycles forever
        # (.wip_* is the serializer's own atomic-write temp, left when
        # the kill lands inside save_model itself)
        for name in os.listdir(directory):
            if name.startswith((".staging_", ".wip_")):
                try:
                    os.remove(os.path.join(directory, name))
                except OSError:
                    pass

    @property
    def best_path(self) -> str:
        return os.path.join(self.directory, "bestModel.zip")

    @property
    def latest_path(self) -> str:
        return os.path.join(self.directory, "latestModel.zip")

    @staticmethod
    def _prev(path: str) -> str:
        return path[:-len(".zip")] + ".prev.zip"

    def _save(self, net, path: str) -> None:
        from ..util import save_model
        from ..util.serialization import CheckpointInvalid, verify_checkpoint
        staging = os.path.join(
            self.directory,
            f".staging_{os.getpid()}_{os.path.basename(path)}")
        try:
            save_model(net, staging)
            verify_checkpoint(staging)      # never publish an invalid zip
            if os.path.exists(path):
                try:
                    # rotate the outgoing model only while it is still a
                    # valid fallback — never clobber a good .prev with a
                    # corrupt current
                    verify_checkpoint(path)
                    os.replace(path, self._prev(path))
                except CheckpointInvalid:
                    pass
            os.replace(staging, path)
        finally:
            if os.path.exists(staging):
                try:
                    os.remove(staging)
                except OSError:
                    pass

    def save_best_model(self, net, score: float) -> None:
        self._save(net, self.best_path)

    def save_latest_model(self, net, score: float) -> None:
        self._save(net, self.latest_path)

    def _load(self, path: str):
        import logging
        from ..util import load_model
        from ..util.serialization import CheckpointInvalid, verify_checkpoint
        for candidate in (path, self._prev(path)):
            if not os.path.exists(candidate):
                continue
            try:
                verify_checkpoint(candidate)
                return load_model(candidate)
            except Exception as e:
                logging.getLogger("deeplearning4j_tpu").warning(
                    "saved model %s unusable (%s: %s) — falling back",
                    candidate, type(e).__name__, e)
        return None

    def get_best_model(self):
        return self._load(self.best_path)

    def get_latest_model(self):
        return self._load(self.latest_path)
