"""Score calculators (parity: reference ``scorecalc/DataSetLossCalculator``)."""

from __future__ import annotations

import numpy as np


class ScoreCalculator:
    def calculate_score(self, net) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over a held-out iterator (parity:
    ``DataSetLossCalculator.java`` with ``average=true``)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, net) -> float:
        total, n = 0.0, 0
        for ds in self.iterator:
            x, y = ds.features, ds.labels
            mask = getattr(ds, "features_mask", None)
            batch = x.shape[0]
            s = net.score_for(x, y, mask) if not _is_graph(net) else \
                net.score_for([x], [y], None if mask is None else [mask])
            total += s * batch
            n += batch
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        return total / max(n, 1) if self.average else total


class EvaluationScoreCalculator(ScoreCalculator):
    """1 - accuracy on a held-out iterator (lower is better, so early stopping
    maximizes accuracy)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, net) -> float:
        ev = net.evaluate(self.iterator)
        return 1.0 - ev.accuracy()


def _is_graph(net) -> bool:
    return type(net).__name__ == "ComputationGraph"
