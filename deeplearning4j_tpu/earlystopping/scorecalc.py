"""Score calculators (parity: reference ``scorecalc/DataSetLossCalculator``)."""

from __future__ import annotations

import numpy as np


class ScoreCalculator:
    def calculate_score(self, net) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over a held-out iterator (parity:
    ``DataSetLossCalculator.java`` with ``average=true``). Pass ``mesh`` to
    shard the held-out batches over a device mesh (the analog of the
    reference's ``SparkDataSetLossCalculator``)."""

    def __init__(self, iterator, average: bool = True, mesh=None):
        self.iterator = iterator
        self.average = average
        self.mesh = mesh
        self._evaluator = None

    def _sharded(self, net):
        from ..parallel.evaluation import ShardedEvaluator
        if self._evaluator is None or self._evaluator.net is not net:
            self._evaluator = ShardedEvaluator(net, self.mesh)
        return self._evaluator

    def calculate_score(self, net) -> float:
        if self.mesh is not None:
            return self._sharded(net).score(
                self.iterator, average=self.average)
        total, n = 0.0, 0
        for ds in self.iterator:
            x, y = ds.features, ds.labels
            mask = getattr(ds, "features_mask", None)
            batch = x.shape[0]
            s = net.score_for(x, y, mask) if not _is_graph(net) else \
                net.score_for([x], [y], None if mask is None else [mask])
            total += s * batch
            n += batch
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        return total / max(n, 1) if self.average else total


class EvaluationScoreCalculator(ScoreCalculator):
    """1 - accuracy on a held-out iterator (lower is better, so early stopping
    maximizes accuracy)."""

    def __init__(self, iterator, mesh=None):
        self.iterator = iterator
        self.mesh = mesh
        self._evaluator = None

    def _sharded(self, net):
        from ..parallel.evaluation import ShardedEvaluator
        if self._evaluator is None or self._evaluator.net is not net:
            self._evaluator = ShardedEvaluator(net, self.mesh)
        return self._evaluator

    def calculate_score(self, net) -> float:
        if self.mesh is not None:
            ev = self._sharded(net).evaluate(self.iterator)
        else:
            ev = net.evaluate(self.iterator)
        return 1.0 - ev.accuracy()


from ..util.netutil import is_graph as _is_graph  # noqa: E402
