"""Early-stopping trainers (parity: reference
``earlystopping/trainer/BaseEarlyStoppingTrainer.java`` — the epoch loop:
fit one epoch → every N epochs compute held-out score → save best → poll
termination conditions; iteration conditions polled per minibatch).
"""

from __future__ import annotations

from typing import Optional

from .config import EarlyStoppingConfiguration, EarlyStoppingResult


class BaseEarlyStoppingTrainer:
    def __init__(self, config: EarlyStoppingConfiguration, net, train_data,
                 watchdog=None):
        self.config = config
        self.net = net
        self.train_data = train_data
        # optional util.durable.StepWatchdog: petted once per minibatch,
        # so a hung dispatch/ingest surfaces as a diagnosed timeout
        # instead of a silent stall
        self.watchdog = watchdog

    def fit(self) -> EarlyStoppingResult:
        net = self.net
        if net.params is None:
            net.init()
        if self.watchdog is not None:
            self.watchdog.arm()
        try:
            return self._fit_loop()
        finally:
            if self.watchdog is not None:
                self.watchdog.disarm()

    def _fit_loop(self) -> EarlyStoppingResult:
        from ..util import faults as _faults
        cfg = self.config
        net = self.net
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()

        best_score: Optional[float] = None
        best_epoch = -1
        score_vs_epoch = {}
        epoch = 0
        reason, details = "epoch_condition", ""

        while True:
            # lazy epoch-start reset: the final epoch (or an
            # iteration-condition stop) never restarts the producer just
            # to discard the work; epoch 0 revives an iterator a previous
            # fit() left exhausted (same contract as run_fit_loop)
            if hasattr(self.train_data, "reset") and (
                    epoch > 0 or (hasattr(self.train_data, "has_next")
                                  and not self.train_data.has_next())):
                self.train_data.reset()
            stop_iteration = None
            for x, y, mask in self._staged_batches():
                _faults.check("training.step", {
                    "model": type(net).__name__, "epoch": epoch,
                    "iteration": net.iteration_count,
                    "kind": "earlystopping"})
                loss = float(self._fit_batch(x, y, mask))
                if self.watchdog is not None:
                    self.watchdog.pet()
                for c in cfg.iteration_termination_conditions:
                    if c.terminate(loss):
                        stop_iteration = c
                        break
                if stop_iteration is not None:
                    break

            if stop_iteration is not None:
                reason = "iteration_condition"
                details = repr(stop_iteration)
                break

            last_score = None
            if epoch % cfg.evaluate_every_n_epochs == 0:
                last_score = float(cfg.score_calculator.calculate_score(net))
                score_vs_epoch[epoch] = last_score
                if best_score is None or last_score < best_score:
                    best_score, best_epoch = last_score, epoch
                    cfg.model_saver.save_best_model(net, last_score)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(net, last_score)
            # epoch conditions are polled EVERY epoch (parity with the
            # reference loop), using the most recent score when this epoch
            # had no evaluation
            poll_score = (last_score if last_score is not None
                          else (score_vs_epoch[max(score_vs_epoch)]
                                if score_vs_epoch else float("inf")))
            stop_epoch = None
            for c in cfg.epoch_termination_conditions:
                if c.terminate(epoch, poll_score):
                    stop_epoch = c
                    break
            if stop_epoch is not None:
                reason = "epoch_condition"
                details = repr(stop_epoch)
                break
            epoch += 1

        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            total_epochs=epoch + 1,
            best_model_epoch=best_epoch,
            best_model_score=best_score if best_score is not None else float("nan"),
            score_vs_epoch=score_vs_epoch,
            best_model=cfg.model_saver.get_best_model(),
        )

    def _fit_batch(self, x, y, mask):
        raise NotImplementedError

    def _staged_batches(self):
        """The ingest-staged view of ``_batches()``: background
        ``jax.device_put`` double-buffering for iterator sources (same
        stage ``fit()`` uses), plain pass-through for single DataSets or
        already-device-staged async iterators."""
        from ..util import ingest as _ingest
        data = self.train_data
        if (hasattr(data, "features") or not _ingest.staging_enabled()
                or _ingest.already_staged(data)):
            yield from self._batches()
            return
        staged = _ingest.stage(self._batches(), stage_name="earlystopping")
        try:
            yield from staged
        finally:
            staged.close()

    def _batches(self):
        """Yield (features, labels, mask) triples from train_data."""
        data = self.train_data
        if hasattr(data, "features"):
            yield (data.features, data.labels,
                   getattr(data, "features_mask", None))
            return
        for item in data:
            if hasattr(item, "features"):
                yield (item.features, item.labels,
                       getattr(item, "features_mask", None))
            else:
                x, y = item[0], item[1]
                yield (x, y, item[2] if len(item) > 2 else None)


class EarlyStoppingTrainer(BaseEarlyStoppingTrainer):
    """For MultiLayerNetwork (parity: ``EarlyStoppingTrainer.java``)."""

    def _fit_batch(self, x, y, mask):
        return self.net.fit_batch(x, y, mask)


class EarlyStoppingGraphTrainer(BaseEarlyStoppingTrainer):
    """For ComputationGraph (parity: ``EarlyStoppingGraphTrainer.java``)."""

    def _fit_batch(self, x, y, mask):
        return self.net.fit_batch(x, y, None if mask is None else [mask])
