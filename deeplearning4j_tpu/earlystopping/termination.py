"""Termination conditions (parity: reference ``earlystopping/termination/``).

Epoch conditions are polled after each epoch's score calculation; iteration
conditions after every minibatch.
"""

from __future__ import annotations

import math
import time
from typing import Optional


class EpochTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = int(max_epochs)

    def terminate(self, epoch: int, score: float) -> bool:
        return epoch + 1 >= self.max_epochs

    def __repr__(self):
        return f"MaxEpochs({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no (sufficient) improvement."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = int(max_epochs_without_improvement)
        self.min_improvement = float(min_improvement)
        self._best: Optional[float] = None
        self._stale = 0

    def initialize(self) -> None:
        self._best, self._stale = None, 0

    def terminate(self, epoch: int, score: float) -> bool:
        if self._best is None or self._best - score > self.min_improvement:
            self._best = min(score, self._best) if self._best is not None else score
            self._stale = 0
            return False
        self._stale += 1
        return self._stale >= self.patience

    def __repr__(self):
        return f"ScoreImprovement(patience={self.patience})"


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score is at/below a target value."""

    def __init__(self, best_expected_score: float):
        self.target = float(best_expected_score)

    def terminate(self, epoch: int, score: float) -> bool:
        return score <= self.target

    def __repr__(self):
        return f"BestScore(target={self.target})"


class MaxTimeTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = float(max_seconds)
        self._start = None

    def initialize(self) -> None:
        self._start = time.monotonic()

    def terminate(self, last_score: float) -> bool:
        if self._start is None:
            self.initialize()
        return time.monotonic() - self._start >= self.max_seconds

    def __repr__(self):
        return f"MaxTime({self.max_seconds}s)"


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort if the score explodes past a ceiling."""

    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def terminate(self, last_score: float) -> bool:
        return last_score > self.max_score

    def __repr__(self):
        return f"MaxScore({self.max_score})"


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    def terminate(self, last_score: float) -> bool:
        return math.isnan(last_score) or math.isinf(last_score)

    def __repr__(self):
        return "InvalidScore()"
