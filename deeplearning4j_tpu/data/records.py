"""Sharded seekable record files: the on-disk dataset format.

Production TPU input stacks (tf.data snapshots, ArrayRecord/Grain, the
reference era's DataVec record files) converge on the same shape: a
dataset is N independent shard files, each a sequence of length+checksum
framed records with an index so any record is one seek away. That shape
is what makes every downstream property cheap — per-host sharding is
file assignment, shuffling is permutation over (shard, record) ids, and
exact mid-epoch resume is "seek these offsets again".

Layout of one ``name-SSSSS-of-NNNNN.rec`` shard::

    header  := b"DL4JREC1" | u32 json_len | header_json
    record  := u32 payload_len | u32 crc32(payload) | payload
    index   := u64 offset[count]          (file offset of each record)
    footer  := u64 index_off | u32 count | u32 crc32(index) | b"DL4JIDX1"

The fixed 24-byte footer at EOF locates the index; the index crc proves
it; each record's crc proves the payload. A shard is written to a
``.tmp`` path and renamed into place on close, so a crashed writer never
leaves a ``.rec`` file at all — and a truncated/torn copy loses its
footer, so it is REFUSED at open rather than silently feeding garbage.

Corrupt-record policy on read: ``corrupt="raise"`` (default — a bad crc
raises :class:`RecordCorruptError`) or ``corrupt="skip"`` (count into
``reader.skipped`` and keep going; the fsck walk uses this).

``python -m deeplearning4j_tpu.data.records --fsck DIR`` walks every
shard set under DIR (header/index/footer structure, every record's
crc32, shard-count contiguity) and exits nonzero with a per-shard
report. jax-free on purpose: the CLI and the chaos tests that reuse
:func:`fsck` pay numpy import only.
"""

from __future__ import annotations

import io
import json
import os
import re
import struct
import zlib
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

FORMAT_VERSION = 1

_FILE_MAGIC = b"DL4JREC1"
_INDEX_MAGIC = b"DL4JIDX1"
_HDR_LEN = struct.Struct("<I")
_REC_HDR = struct.Struct("<II")           # payload_len, crc32
_FOOTER = struct.Struct("<QII8s")         # index_off, count, index_crc, magic

SHARD_RE = re.compile(r"^(?P<name>.+)-(?P<idx>\d{5})-of-(?P<of>\d{5})\.rec$")


class RecordFormatError(Exception):
    """Structural damage: bad magic, missing/corrupt index footer,
    offsets outside the file. A shard in this state is refused at open —
    no record of it can be trusted."""


class RecordCorruptError(RecordFormatError):
    """One record's payload failed its crc32 or was truncated."""


class ShardSetError(Exception):
    """Set-level damage: missing shard index, inconsistent ``-of-N``,
    duplicate indices, or no shards at all."""


def shard_filename(name: str, index: int, num_shards: int) -> str:
    if not 0 <= index < num_shards:
        raise ValueError(f"shard index {index} outside 0..{num_shards - 1}")
    return f"{name}-{index:05d}-of-{num_shards:05d}.rec"


# ----------------------------------------------------------------------
# example serialization (dict of named numpy arrays <-> bytes)
# ----------------------------------------------------------------------

_KEY_LEN = struct.Struct("<H")
_ARR_HDR = struct.Struct("<B")            # ndim (and array count)
_DIM = struct.Struct("<q")


def encode_example(arrays: Dict[str, np.ndarray]) -> bytes:
    """Serialize a dict of named numpy arrays (sorted key order, C-order
    raw bytes — deterministic: the same dict always encodes to the same
    payload, so record crcs are stable across writers)."""
    out = io.BytesIO()
    out.write(_ARR_HDR.pack(len(arrays)))
    for key in sorted(arrays):
        a = np.asarray(arrays[key])
        if not a.flags["C_CONTIGUOUS"]:
            # NB: np.ascontiguousarray unconditionally would promote 0-d
            # scalars to 1-d and corrupt the round-tripped shape
            a = np.ascontiguousarray(a)
        kb = key.encode("utf-8")
        out.write(_KEY_LEN.pack(len(kb)))
        out.write(kb)
        db = a.dtype.str.encode("ascii")
        out.write(_KEY_LEN.pack(len(db)))
        out.write(db)
        out.write(_ARR_HDR.pack(a.ndim))
        for d in a.shape:
            out.write(_DIM.pack(d))
        out.write(a.tobytes())
    return out.getvalue()


def decode_example(payload: bytes) -> Dict[str, np.ndarray]:
    buf = io.BytesIO(payload)

    def take(n: int) -> bytes:
        b = buf.read(n)
        if len(b) != n:
            raise RecordCorruptError("example payload truncated")
        return b

    (count,) = _ARR_HDR.unpack(take(1))
    out: Dict[str, np.ndarray] = {}
    for _ in range(count):
        (klen,) = _KEY_LEN.unpack(take(2))
        key = take(klen).decode("utf-8")
        (dlen,) = _KEY_LEN.unpack(take(2))
        dtype = np.dtype(take(dlen).decode("ascii"))
        (ndim,) = _ARR_HDR.unpack(take(1))
        shape = tuple(_DIM.unpack(take(8))[0] for _ in range(ndim))
        nbytes = int(dtype.itemsize * int(np.prod(shape, dtype=np.int64)))
        out[key] = np.frombuffer(take(nbytes), dtype=dtype).reshape(shape)
    return out


# ----------------------------------------------------------------------
# writer
# ----------------------------------------------------------------------

class ShardWriter:
    """Append records to one shard; ``close()`` writes the index footer,
    fsyncs and atomically renames ``path.tmp`` -> ``path``. Usable as a
    context manager (exceptions abandon the .tmp file — no torn .rec)."""

    def __init__(self, path: str, *, name: str, shard_index: int,
                 num_shards: int):
        self.path = path
        self._tmp = path + ".tmp"
        self._offsets: List[int] = []
        self._f = open(self._tmp, "wb")
        header = json.dumps({
            "version": FORMAT_VERSION, "name": name,
            "shard": int(shard_index), "of": int(num_shards)},
            sort_keys=True).encode()
        self._f.write(_FILE_MAGIC)
        self._f.write(_HDR_LEN.pack(len(header)))
        self._f.write(header)

    def append(self, payload: bytes) -> int:
        """Write one record; returns its record index within the shard."""
        if self._f is None:
            raise ValueError("writer is closed")
        self._offsets.append(self._f.tell())
        self._f.write(_REC_HDR.pack(len(payload),
                                    zlib.crc32(payload) & 0xFFFFFFFF))
        self._f.write(payload)
        return len(self._offsets) - 1

    def __len__(self) -> int:
        return len(self._offsets)

    def close(self) -> str:
        if self._f is None:
            return self.path
        index_off = self._f.tell()
        index = b"".join(struct.pack("<Q", o) for o in self._offsets)
        self._f.write(index)
        self._f.write(_FOOTER.pack(index_off, len(self._offsets),
                                   zlib.crc32(index) & 0xFFFFFFFF,
                                   _INDEX_MAGIC))
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._f = None
        os.replace(self._tmp, self.path)
        return self.path

    def abandon(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
            try:
                os.remove(self._tmp)
            except OSError:
                pass

    def __enter__(self) -> "ShardWriter":
        return self

    def __exit__(self, exc_type, *exc) -> bool:
        if exc_type is None:
            self.close()
        else:
            self.abandon()
        return False


def write_shard_set(directory: str, name: str,
                    examples: Iterable[Dict[str, np.ndarray]],
                    num_shards: int, *, split: str = "round_robin",
                    encode=encode_example) -> List[str]:
    """Write ``examples`` (dicts of named arrays, or pre-encoded bytes
    via ``encode=None``) into ``num_shards`` shard files.

    ``split="round_robin"`` streams (example i -> shard i % N; works on
    any iterable); ``split="contiguous"`` keeps the original order as N
    consecutive chunks (needs a sized sequence) — the mode that makes a
    1-host unshuffled read bit-identical to iterating the source.
    """
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    os.makedirs(directory, exist_ok=True)
    writers = [ShardWriter(os.path.join(
        directory, shard_filename(name, i, num_shards)),
        name=name, shard_index=i, num_shards=num_shards)
        for i in range(num_shards)]
    try:
        if split == "round_robin":
            for i, ex in enumerate(examples):
                writers[i % num_shards].append(
                    encode(ex) if encode is not None else ex)
        elif split == "contiguous":
            examples = list(examples)
            bounds = np.linspace(0, len(examples), num_shards + 1)
            for i, ex in enumerate(examples):
                shard = int(np.searchsorted(bounds, i, side="right")) - 1
                writers[min(shard, num_shards - 1)].append(
                    encode(ex) if encode is not None else ex)
        else:
            raise ValueError(f"unknown split mode {split!r}")
        return [w.close() for w in writers]
    except BaseException:
        for w in writers:
            w.abandon()
        raise


# ----------------------------------------------------------------------
# reader
# ----------------------------------------------------------------------

class ShardReader:
    """One shard file: sequential iteration + O(1) ``read(i)`` via the
    index footer. Open validates magic + footer + index crc + offset
    sanity and REFUSES structurally damaged files; per-record crc
    failures follow the ``corrupt`` policy ("raise" | "skip")."""

    def __init__(self, path: str, *, corrupt: str = "raise"):
        if corrupt not in ("raise", "skip"):
            raise ValueError(f"corrupt policy must be 'raise' or 'skip', "
                             f"got {corrupt!r}")
        self.path = path
        self.corrupt = corrupt
        self.skipped = 0
        self._f = open(path, "rb")
        try:
            self._open()
        except BaseException:
            self._f.close()
            raise

    def _open(self) -> None:
        size = os.fstat(self._f.fileno()).st_size
        min_size = len(_FILE_MAGIC) + _HDR_LEN.size + _FOOTER.size
        if size < min_size:
            raise RecordFormatError(
                f"{self.path}: {size} bytes — too short to be a shard "
                "(truncated?)")
        if self._f.read(len(_FILE_MAGIC)) != _FILE_MAGIC:
            raise RecordFormatError(f"{self.path}: bad file magic")
        (hdr_len,) = _HDR_LEN.unpack(self._f.read(_HDR_LEN.size))
        try:
            self.header = json.loads(self._f.read(hdr_len))
        except ValueError as e:
            raise RecordFormatError(f"{self.path}: unreadable header ({e})")
        self._f.seek(size - _FOOTER.size)
        index_off, count, index_crc, magic = _FOOTER.unpack(
            self._f.read(_FOOTER.size))
        if magic != _INDEX_MAGIC:
            raise RecordFormatError(
                f"{self.path}: no index footer (torn or in-progress "
                "write — refusing the whole shard)")
        if index_off + 8 * count != size - _FOOTER.size:
            raise RecordFormatError(
                f"{self.path}: index footer geometry inconsistent "
                f"(off={index_off}, count={count}, size={size})")
        self._f.seek(index_off)
        index = self._f.read(8 * count)
        if zlib.crc32(index) & 0xFFFFFFFF != index_crc:
            raise RecordFormatError(f"{self.path}: index crc32 mismatch")
        self.offsets = [struct.unpack_from("<Q", index, 8 * i)[0]
                        for i in range(count)]
        prev = 0
        for o in self.offsets:
            if o < prev or o + _REC_HDR.size > index_off:
                raise RecordFormatError(
                    f"{self.path}: index offset {o} out of bounds")
            prev = o
        self._data_end = index_off

    def __len__(self) -> int:
        return len(self.offsets)

    def read(self, i: int) -> Optional[bytes]:
        """Record ``i``'s payload, crc-verified. Under ``corrupt="skip"``
        a bad record returns None (and counts into ``skipped``)."""
        if not 0 <= i < len(self.offsets):
            raise IndexError(f"record {i} outside 0..{len(self) - 1}")
        self._f.seek(self.offsets[i])
        hdr = self._f.read(_REC_HDR.size)
        problem = None
        payload = b""
        if len(hdr) != _REC_HDR.size:
            problem = "record header truncated"
        else:
            length, crc = _REC_HDR.unpack(hdr)
            if self.offsets[i] + _REC_HDR.size + length > self._data_end:
                problem = f"record length {length} runs past the data region"
            else:
                payload = self._f.read(length)
                if len(payload) != length:
                    problem = "record payload truncated"
                elif zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    problem = "crc32 mismatch"
        if problem is None:
            return payload
        if self.corrupt == "skip":
            self.skipped += 1
            return None
        raise RecordCorruptError(f"{self.path}: record {i}: {problem}")

    def __iter__(self):
        """Yield (record_index, payload) for every GOOD record (corrupt
        ones raise or are skipped per policy)."""
        for i in range(len(self)):
            payload = self.read(i)
            if payload is not None:
                yield i, payload

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "ShardReader":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


# ----------------------------------------------------------------------
# shard sets
# ----------------------------------------------------------------------

def _discover(directory: str) -> Dict[Tuple[str, int], Dict[int, str]]:
    """{(name, of): {index: filename}} for every .rec under directory."""
    out: Dict[Tuple[str, int], Dict[int, str]] = {}
    for fn in sorted(os.listdir(directory)):
        m = SHARD_RE.match(fn)
        if m is None:
            continue
        key = (m.group("name"), int(m.group("of")))
        out.setdefault(key, {})[int(m.group("idx"))] = fn
    return out


class ShardSet:
    """The complete shard set ``name-*-of-N.rec`` in one directory.

    Open REFUSES an incomplete set (missing index, duplicate/extra
    indices, inconsistent ``-of-N``): training over a silently partial
    dataset is the failure mode this exists to prevent. Readers open
    lazily and are cached; ``corrupt`` is passed through to them.
    """

    def __init__(self, directory: str, name: Optional[str] = None, *,
                 corrupt: str = "raise"):
        self.directory = directory
        self.corrupt = corrupt
        sets = _discover(directory)
        if name is not None:
            sets = {k: v for k, v in sets.items() if k[0] == name}
        if not sets:
            raise ShardSetError(
                f"{directory}: no shard files"
                + (f" named {name!r}" if name else ""))
        names = {k[0] for k in sets}
        if len(names) > 1:
            raise ShardSetError(
                f"{directory}: multiple shard sets {sorted(names)} — "
                "pass name= to pick one")
        self.name = next(iter(names))
        if len(sets) > 1:
            raise ShardSetError(
                f"{directory}: {self.name!r} has shards from different "
                f"-of-N generations: {sorted(k[1] for k in sets)}")
        (_, of), files = next(iter(sets.items()))
        missing = sorted(set(range(of)) - set(files))
        if missing:
            raise ShardSetError(
                f"{directory}: {self.name!r} is missing shard(s) "
                f"{missing} of {of} — refusing the set")
        extra = sorted(set(files) - set(range(of)))
        if extra:
            raise ShardSetError(
                f"{directory}: {self.name!r} has out-of-range shard "
                f"indices {extra} for -of-{of}")
        self.num_shards = of
        self._files = files
        self._readers: Dict[int, ShardReader] = {}

    def reader(self, i: int) -> ShardReader:
        r = self._readers.get(i)
        if r is None:
            r = ShardReader(os.path.join(self.directory, self._files[i]),
                            corrupt=self.corrupt)
            if (r.header.get("shard"), r.header.get("of")) != \
                    (i, self.num_shards):
                raise ShardSetError(
                    f"{self._files[i]}: header says shard "
                    f"{r.header.get('shard')}/{r.header.get('of')}, "
                    f"filename says {i}/{self.num_shards}")
            self._readers[i] = r
        return r

    def record_count(self, i: int) -> int:
        return len(self.reader(i))

    def total_records(self) -> int:
        return sum(self.record_count(i) for i in range(self.num_shards))

    @property
    def skipped(self) -> int:
        return sum(r.skipped for r in self._readers.values())

    def close(self) -> None:
        for r in self._readers.values():
            r.close()
        self._readers.clear()


# ----------------------------------------------------------------------
# fsck
# ----------------------------------------------------------------------

def fsck(directory: str, name: Optional[str] = None) -> dict:
    """Verify every shard set under ``directory``: structure (magic /
    index footer / offsets), every record's crc32, and shard-count
    contiguity. Returns a report dict with ``report["ok"]``; the CLI
    prints it and exits nonzero when not ok."""
    sets = _discover(directory)
    if name is not None:
        sets = {k: v for k, v in sets.items() if k[0] == name}
    report: dict = {"directory": directory, "sets": {}, "ok": True}
    if not sets:
        report["ok"] = False
        report["error"] = ("no shard files"
                           + (f" named {name!r}" if name else ""))
        return report
    by_name: Dict[str, List[Tuple[int, Dict[int, str]]]] = {}
    for (nm, of), files in sorted(sets.items()):
        by_name.setdefault(nm, []).append((of, files))
    for nm, gens in by_name.items():
        entry: dict = {"shards": {}, "errors": []}
        report["sets"][nm] = entry
        if len(gens) > 1:
            entry["errors"].append(
                f"mixed -of-N generations: {sorted(of for of, _ in gens)}")
        of = gens[0][0] if len(gens) == 1 else None
        files: Dict[int, str] = {}
        for _, fs in gens:
            files.update(fs)
        if of is not None:
            missing = sorted(set(range(of)) - set(files))
            if missing:
                entry["errors"].append(f"missing shard(s) {missing} of {of}")
            entry["num_shards"] = of
        for idx in sorted(files):
            fn = files[idx]
            shard: dict = {"records": 0, "bad_records": 0, "error": None}
            entry["shards"][fn] = shard
            try:
                with ShardReader(os.path.join(directory, fn),
                                 corrupt="skip") as r:
                    n = sum(1 for _ in r)
                    shard["records"] = n
                    shard["bad_records"] = r.skipped
                    shard["indexed"] = len(r)
            except RecordFormatError as e:
                shard["error"] = str(e)
            if shard["error"] or shard["bad_records"]:
                entry["errors"].append(f"{fn}: "
                                       + (shard["error"]
                                          or f"{shard['bad_records']} "
                                             "corrupt record(s)"))
        if entry["errors"]:
            report["ok"] = False
    return report


def format_report(report: dict) -> str:
    lines = [f"fsck {report['directory']}"]
    if report.get("error"):
        lines.append(f"  ERROR: {report['error']}")
    for nm, entry in report.get("sets", {}).items():
        n = entry.get("num_shards", "?")
        lines.append(f"  set {nm!r} (-of-{n}):")
        for fn, shard in entry["shards"].items():
            status = (f"ERROR: {shard['error']}" if shard["error"] else
                      f"{shard['records']} records"
                      + (f", {shard['bad_records']} CORRUPT"
                         if shard["bad_records"] else " ok"))
            lines.append(f"    {fn}: {status}")
        for err in entry["errors"]:
            lines.append(f"    SET ERROR: {err}")
    lines.append("FSCK " + ("OK" if report["ok"] else "FAILED"))
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.data.records",
        description="Verify sharded record files (crc32, index footers, "
                    "shard-count contiguity).")
    p.add_argument("--fsck", metavar="DIR", required=True,
                   help="directory holding name-SSSSS-of-NNNNN.rec shards")
    p.add_argument("--name", default=None,
                   help="restrict to one shard-set name")
    args = p.parse_args(argv)
    report = fsck(args.fsck, args.name)
    print(format_report(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
