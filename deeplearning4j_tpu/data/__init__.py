"""Sharded-record input pipeline (SURVEY §7 "ImageNet-scale input").

The training-side millions-of-examples story: seekable sharded record
files (:mod:`.records` — crc32-framed records, O(1) index footer, fsck),
and a composable pipeline (:mod:`.pipeline` — deterministic per-host
shard assignment, epoch-seeded shuffles, a jit-compiled augmentation
stage, and a ``DataSetIterator`` with the full seekable-cursor protocol
so ``DurableSession`` resumes a preempted mid-epoch run bit-exactly).

Lazy attribute surface: ``python -m deeplearning4j_tpu.data.records``
(the fsck CLI) must not import the pipeline's jax surface just to walk
shard files.
"""

_FROM = {
    name: "records" for name in (
        "RecordCorruptError", "RecordFormatError", "ShardReader",
        "ShardSet", "ShardSetError", "ShardWriter", "decode_example",
        "encode_example", "fsck", "shard_filename", "write_shard_set")
}
_FROM.update({
    name: "pipeline" for name in (
        "Augment", "AugmentStage", "RecordDataSetIterator",
        "assignment_for_round", "shard_assignment")
})

__all__ = sorted(_FROM) + ["pipeline", "records"]


def __getattr__(name):
    import importlib
    if name in ("records", "pipeline"):
        mod = importlib.import_module(f"{__name__}.{name}")
        globals()[name] = mod
        return mod
    if name in _FROM:
        mod = importlib.import_module(f"{__name__}.{_FROM[name]}")
        value = getattr(mod, name)
        globals()[name] = value
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
