"""Composable input pipeline over sharded record files.

The four stages production TPU input stacks converge on (tf.data /
Grain), composed with this repo's substrate:

1. **Per-host shard assignment** — :func:`shard_assignment` is a pure
   function of (num_shards, member set, host): strided over the SORTED
   member list, so it is disjoint and covering by construction and every
   host computes the same answer with no coordination. Elastic fleets
   derive the member set from the membership log
   (:func:`assignment_for_round`), so a host dropout reassigns shards
   deterministically at the round it became effective.
2. **Epoch-seeded shuffles** — the shard ORDER is permuted per epoch and
   a bounded within-shard(-window) shuffle buffer mixes records, both
   seeded arithmetically (blake2s of (seed, epoch) — never python
   ``hash()``, which is salted per process and would break restart
   determinism).
3. **A jit-compiled augmentation stage** (:class:`Augment`) — random
   crop, horizontal flip, scale/normalize in ONE dispatch per batch,
   guarded by ``util.xla.retrace_guard`` like every other jit site. The
   rng follows the PR-4 counter scheme: the key is
   ``fold_name(key(seed), "augment")`` folded with the GLOBAL batch
   counter inside the jitted program, and the counter rides the cursor —
   so a resumed run re-augments batch n bit-identically.
4. **Batching into ``DataSet``** — :class:`RecordDataSetIterator` is a
   normal ``DataSetIterator``: ``fit()`` wraps it in the PR-3 ``stage()``
   double-buffered device staging (record decode + augment dispatch run
   on the staging producer thread, overlapping the in-flight step), and
   it implements the FULL seekable-cursor protocol — ``state()`` /
   ``restore()`` capture (epoch, shard position, record offset, shuffle
   buffer refs + rng state, batch counter), so ``DurableSession``
   resumes a preempted mid-epoch run replaying zero batches and
   skipping none.

Cursor note: the shuffle buffer holds READ-AHEAD records; serializing
their bytes into every checkpoint would bloat cursors, so ``state()``
records each buffered record's (shard-position, record-index) REFERENCE
and ``restore()`` re-fetches them — O(buffer) index-backed seeks.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..datasets.dataset import DataSet
from ..datasets.iterator import DataSetIterator
from ..util import ingest as _ingest
from .records import ShardSet, decode_example

_CURSOR_VERSION = 1


def _stable_seed(*parts) -> int:
    """Process-restart-stable 31-bit seed from arbitrary parts (python
    ``hash()`` is salted per interpreter — the elastic determinism trap)."""
    h = hashlib.blake2s("\x1f".join(str(p) for p in parts).encode(),
                        digest_size=4).digest()
    return int.from_bytes(h, "big") & 0x7FFFFFFF


# ----------------------------------------------------------------------
# per-host shard assignment
# ----------------------------------------------------------------------

def shard_assignment(num_shards: int, members: Sequence[str],
                     host: str) -> Tuple[int, ...]:
    """The shards ``host`` owns among ``members``: strided over the
    sorted member list. Disjoint and covering by construction,
    order-insensitive in ``members``, and a pure function — every host
    computes the fleet's whole assignment without coordination."""
    ms = sorted(set(members))
    if host not in ms:
        raise ValueError(f"host {host!r} not in members {ms}")
    if num_shards < len(ms):
        raise ValueError(
            f"{num_shards} shard(s) cannot feed {len(ms)} hosts — every "
            "host must own at least one shard (write more shards)")
    i = ms.index(host)
    return tuple(s for s in range(num_shards) if s % len(ms) == i)


def assignment_for_round(num_shards: int, coordinator, round_: int,
                         host: str) -> Tuple[int, ...]:
    """Shard assignment under the elastic membership log: the member set
    is ``ElasticCoordinator.members_for_round(round_)``, so every
    surviving host derives the same post-eviction assignment at the same
    effective round (the log is the shared truth; no extra agreement)."""
    return shard_assignment(
        num_shards, coordinator.members_for_round(round_), host)


# ----------------------------------------------------------------------
# jit-compiled augmentation
# ----------------------------------------------------------------------

@dataclass(frozen=True)
class Augment:
    """Per-batch augmentation config, lowered into ONE jitted dispatch.

    ``crop_pad``/``flip`` need NHWC image batches ``[b, h, w, c]``;
    ``scale``/``mean``/``std`` apply to any shape (flat feature vectors
    included). ``scale`` is applied first (e.g. ``1/255`` for uint8
    image records — store bytes, normalize on device).
    """
    crop_pad: int = 0
    flip: bool = False
    scale: Optional[float] = None
    mean: Optional[Tuple[float, ...]] = None
    std: Optional[Tuple[float, ...]] = None

    @property
    def needs_images(self) -> bool:
        return bool(self.crop_pad or self.flip)


class AugmentStage:
    """The compiled stage: ``stage(features, batch_index)`` returns the
    augmented device batch. RNG = ``fold_name(key(seed), "augment")``
    folded with the batch counter INSIDE the program — one dispatch, no
    per-batch host key derivation, bit-exact replay from the cursor's
    counter."""

    def __init__(self, aug: Augment, seed: int, *,
                 stage_name: str = "records", registry=None):
        self.aug = aug
        self.seed = int(seed)
        self.stage_name = stage_name
        self._seconds = _ingest.augment_seconds_counter(registry)
        self._fn = None
        self._registry = registry

    def _build(self):
        import jax
        import jax.numpy as jnp

        from .. import rng as _rng
        from ..util import xla as _xla

        aug = self.aug
        base_key = _rng.fold_name(_rng.key(self.seed), "augment")
        mean = (None if aug.mean is None
                else jnp.asarray(aug.mean, jnp.float32))
        std = (None if aug.std is None
               else jnp.asarray(aug.std, jnp.float32))

        def fn(x, n):
            key = jax.random.fold_in(base_key, n)
            x = x.astype(jnp.float32)
            if aug.scale is not None:
                x = x * jnp.float32(aug.scale)
            if aug.crop_pad:
                p = aug.crop_pad
                k_crop, key = jax.random.split(key)
                padded = jnp.pad(x, ((0, 0), (p, p), (p, p), (0, 0)))
                off = jax.random.randint(
                    k_crop, (x.shape[0], 2), 0, 2 * p + 1)

                def crop(img, o):
                    return jax.lax.dynamic_slice(
                        img, (o[0], o[1], 0),
                        (x.shape[1], x.shape[2], x.shape[3]))

                x = jax.vmap(crop)(padded, off)
            if aug.flip:
                k_flip, key = jax.random.split(key)
                m = jax.random.bernoulli(k_flip, 0.5, (x.shape[0],))
                x = jnp.where(m[:, None, None, None], x[:, :, ::-1, :], x)
            if mean is not None:
                x = x - mean
            if std is not None:
                x = x / std
            return x

        return _xla.retrace_guard(jax.jit(fn), "pipeline.augment",
                                  self._registry)

    def __call__(self, features: np.ndarray, batch_index: int):
        if self.aug.needs_images and features.ndim != 4:
            raise ValueError(
                "crop/flip augmentation needs NHWC image batches "
                f"[b, h, w, c]; got shape {features.shape}")
        if self._fn is None:
            self._fn = self._build()
        t0 = time.perf_counter()
        out = self._fn(features, np.uint32(batch_index))
        self._seconds.inc(time.perf_counter() - t0, stage=self.stage_name)
        return out


# ----------------------------------------------------------------------
# the iterator
# ----------------------------------------------------------------------

class RecordDataSetIterator(DataSetIterator):
    """``DataSetIterator`` over this host's shards of a record set.

    Per epoch: the assigned shards are read in an epoch-seeded permuted
    order; records pass through a bounded shuffle buffer (deterministic
    swap-pop draws from a seeded rng); ``batch_size`` examples stack
    into one ``DataSet``, optionally through the jitted
    :class:`Augment` stage. ``reset()`` advances to the next epoch's
    shuffles (``reshuffle_each_epoch=False`` replays the same epoch —
    evaluation semantics).

    Seekable-cursor protocol: ``state()`` (cheap, JSON-serializable) /
    ``restore(state)`` on an equivalently-constructed iterator reproduce
    the remaining batch stream bit-exactly — including augmentation,
    whose rng is keyed by the global batch counter in the cursor.
    """

    def __init__(self, directory: str, name: Optional[str] = None, *,
                 batch_size: int, features_key: str = "features",
                 labels_key: Optional[str] = "labels",
                 hosts: Sequence[str] = ("host0",),
                 host: Optional[str] = None,
                 seed: int = 0, shuffle_shards: bool = True,
                 shuffle_buffer: int = 0, augment: Optional[Augment] = None,
                 drop_remainder: bool = False,
                 reshuffle_each_epoch: bool = True,
                 corrupt: str = "raise", stage_name: str = "records",
                 registry=None):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self._set = ShardSet(directory, name, corrupt=corrupt)
        self.hosts = tuple(hosts)
        self.host = self.hosts[0] if host is None else host
        self.assigned = shard_assignment(self._set.num_shards, self.hosts,
                                         self.host)
        self._batch = int(batch_size)
        self.features_key = features_key
        self.labels_key = labels_key
        self.seed = int(seed)
        self.shuffle_shards = shuffle_shards
        self.shuffle_buffer = max(0, int(shuffle_buffer))
        self.drop_remainder = drop_remainder
        self.reshuffle_each_epoch = reshuffle_each_epoch
        self.stage_name = stage_name
        self._counts = {s: self._set.record_count(s) for s in self.assigned}
        self._epoch_total = sum(self._counts.values())
        if augment is None or isinstance(augment, AugmentStage):
            # a pre-built stage may be SHARED across iterators (e.g. a
            # warm-up and a timed run reusing one compiled program)
            self._augment = augment
        else:
            self._augment = AugmentStage(augment, seed,
                                         stage_name=stage_name,
                                         registry=registry)
        self._read_ctr = _ingest.records_read_counter(registry)
        self._skip_ctr = _ingest.records_skipped_counter(registry)
        self._batch_ctr = _ingest.pipeline_batches_counter(registry)
        self._skipped_seen = 0
        self._batch_index = 0           # GLOBAL: the augmentation counter
        self._init_epoch(0)

    # -- epoch machinery ------------------------------------------------

    def _init_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)
        order = list(self.assigned)
        if self.shuffle_shards:
            perm = np.random.default_rng(_stable_seed(
                self.seed, "shards", epoch)).permutation(len(order))
            order = [order[i] for i in perm]
        self._perm: List[int] = order
        self._rng = (np.random.default_rng(_stable_seed(
            self.seed, "buffer", epoch)) if self.shuffle_buffer else None)
        self._shard_pos = 0             # index into self._perm
        self._rec_idx = 0               # next record within current shard
        self._buffer: List[Tuple[Tuple[int, int], Dict[str, np.ndarray]]] = []
        self._emitted = 0

    def reset(self) -> None:
        self._init_epoch(self._epoch + 1 if self.reshuffle_each_epoch
                         else self._epoch)

    # -- record stream --------------------------------------------------

    def _fetch(self, shard_pos: int, rec_idx: int) \
            -> Optional[Dict[str, np.ndarray]]:
        payload = self._set.reader(self._perm[shard_pos]).read(rec_idx)
        if payload is None:             # corrupt-skip policy
            return None
        return decode_example(payload)

    def _pull(self) -> Optional[Tuple[Tuple[int, int],
                                      Dict[str, np.ndarray]]]:
        while self._shard_pos < len(self._perm):
            shard = self._perm[self._shard_pos]
            if self._rec_idx >= self._counts[shard]:
                self._shard_pos += 1
                self._rec_idx = 0
                continue
            ref = (self._shard_pos, self._rec_idx)
            self._rec_idx += 1
            ex = self._fetch(*ref)
            if ex is None:
                continue
            self._read_ctr.inc(stage=self.stage_name)
            return ref, ex
        return None

    def _remaining_stream(self) -> int:
        done = sum(self._counts[s] for s in self._perm[:self._shard_pos])
        return self._epoch_total - done - self._rec_idx

    def _next_example(self) -> Optional[Dict[str, np.ndarray]]:
        if self.shuffle_buffer <= 0:
            r = self._pull()
            return None if r is None else r[1]
        while len(self._buffer) < self.shuffle_buffer:
            r = self._pull()
            if r is None:
                break
            self._buffer.append(r)
        if not self._buffer:
            return None
        j = int(self._rng.integers(len(self._buffer)))
        _, ex = self._buffer.pop(j)
        return ex

    # -- DataSetIterator contract ---------------------------------------

    @property
    def batch_size(self) -> int:
        return self._batch

    def total_examples(self) -> int:
        """Records this host owns per epoch (pre-corrupt-skip)."""
        return self._epoch_total

    def has_next(self) -> bool:
        remaining = len(self._buffer) + self._remaining_stream()
        need = self._batch if self.drop_remainder else 1
        return remaining >= need

    def __iter__(self):
        # has_next() counts corrupt records it cannot see past (the skip
        # policy only discovers them on read), so a fully-corrupt tail
        # can make next() come up empty AFTER has_next() said True — end
        # the stream instead of letting the StopIteration escape inside
        # a generator frame (PEP 479 would turn it into a RuntimeError)
        while self.has_next():
            try:
                yield self.next()
            except StopIteration:
                return

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        feats, labels = [], []
        for _ in range(self._batch):
            ex = self._next_example()
            if ex is None:
                break
            feats.append(ex[self.features_key])
            if self.labels_key is not None:
                labels.append(ex[self.labels_key])
        # surface corrupt-skips into the registry BEFORE any end-of-stream
        # raise — a fully-corrupt tail must still show up on monitoring
        self._flush_skips()
        if not feats or (self.drop_remainder and len(feats) < self._batch):
            raise StopIteration
        x = np.stack(feats)
        y = np.stack(labels) if labels else None
        if self._augment is not None:
            x = self._augment(x, self._batch_index)
        self._batch_index += 1
        self._emitted += len(feats)
        self._batch_ctr.inc(stage=self.stage_name)
        return DataSet(x, y)

    def _flush_skips(self) -> None:
        skipped = self._set.skipped
        if skipped > self._skipped_seen:
            self._skip_ctr.inc(skipped - self._skipped_seen,
                               stage=self.stage_name)
            self._skipped_seen = skipped

    # -- seekable cursor protocol ---------------------------------------

    def state(self) -> dict:
        rng_state = None
        if self._rng is not None:
            rng_state = self._rng.bit_generator.state
        return {
            "version": _CURSOR_VERSION,
            "num_shards": self._set.num_shards,
            "host": self.host,
            "members": sorted(set(self.hosts)),
            "epoch": self._epoch,
            "shard_pos": self._shard_pos,
            "rec_idx": self._rec_idx,
            "buffer": [[sp, ri] for (sp, ri), _ in self._buffer],
            "rng": rng_state,
            "batch_index": self._batch_index,
            "emitted": self._emitted,
        }

    def restore(self, state: dict) -> None:
        if state.get("version") != _CURSOR_VERSION:
            raise ValueError(
                f"unsupported cursor version {state.get('version')!r}")
        if state.get("num_shards") != self._set.num_shards \
                or state.get("host") != self.host:
            raise ValueError(
                "cursor belongs to a different pipeline: cursor is "
                f"host={state.get('host')!r} over {state.get('num_shards')}"
                f" shards, this iterator is host={self.host!r} over "
                f"{self._set.num_shards}")
        if state.get("members") != sorted(set(self.hosts)):
            # same host name + shard count but a DIFFERENT member set
            # changes the shard assignment: shard_pos/buffer refs would
            # silently resolve to other hosts' records
            raise ValueError(
                "cursor belongs to a different fleet membership: cursor "
                f"saw members {state.get('members')}, this iterator has "
                f"{sorted(set(self.hosts))} — resuming across a resize "
                "needs a fresh epoch, not a cursor restore")
        if state["buffer"] and self.shuffle_buffer <= 0:
            raise ValueError(
                "cursor carries shuffle-buffer contents but this iterator "
                "was built with shuffle_buffer=0 — same pipeline config "
                "required for exact resume")
        self._init_epoch(int(state["epoch"]))
        self._shard_pos = int(state["shard_pos"])
        self._rec_idx = int(state["rec_idx"])
        for sp, ri in state["buffer"]:
            ex = self._fetch(int(sp), int(ri))
            if ex is None:
                raise ValueError(
                    f"cursor references record {(sp, ri)} that no longer "
                    "decodes — the shard set changed since the snapshot")
            self._buffer.append(((int(sp), int(ri)), ex))
        if self._rng is not None:
            if state.get("rng") is None:
                raise ValueError(
                    "cursor has no shuffle-buffer rng state but this "
                    "iterator shuffles — same pipeline config required")
            self._rng.bit_generator.state = state["rng"]
        self._batch_index = int(state["batch_index"])
        self._emitted = int(state["emitted"])

    def close(self) -> None:
        self._set.close()
