"""MultiLayerNetwork: the sequential-stack runtime model.

Parity: reference ``nn/multilayer/MultiLayerNetwork.java`` —
``init`` (``:368``), ``feedForward`` (``:627``), ``output`` (``:1581``),
``fit(DataSetIterator)`` (``:1037``), ``computeGradientAndScore`` (``:1867``),
``doTruncatedBPTT`` (``:1079``), ``rnnTimeStep`` (``:2274``), ``score``
(``:1900``).

TPU-native design (NOT a port):
  - Parameters are a pytree ``{"layer_0": {...}, ...}`` — not the reference's
    single flattened F-order buffer with per-layer views
    (``MultiLayerNetwork.java:368`` flattenedParams). XLA handles memory
    layout; pytrees keep sharding/checkpointing structural.
  - There is ONE jitted train step (donated params + optimizer state) that
    fuses: forward through all layers, loss + l1/l2, ``jax.grad`` backward,
    gradient normalization, and the updater apply. The reference's
    Solver → ConvexOptimizer → Updater call chain (``Solver.java:41``,
    ``StochasticGradientDescent.java:50-72``) collapses into this one
    XLA program — no per-layer dispatch, no JNI hops.
  - Backprop is autodiff through the forward functions; the reference's
    hand-written ``calcBackpropGradients`` reverse loop
    (``MultiLayerNetwork.java:1123-1190``) has no analog by design.
  - Non-param layer state (BatchNorm running stats) and recurrent carry
    (LSTM h/c) are threaded functionally and returned from the step.
  - The iteration counter is a traced scalar so LR schedules compile into
    the step instead of recompiling per iteration.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes as _dtypes
from .. import losses as _losses
from .. import rng as _rng
from ..optimize import updaters as _updaters
from ..util import health as _health
from ..util import xla as _xla
from ..util.netutil import note_streamed_steps as _note_streamed_steps
from ..util.netutil import precheck_streamed_steps as _precheck_streamed_steps
from .conf.multi_layer import MultiLayerConfiguration
from .conf.preprocessors import call_preprocessor

Pytree = Any


def _layer_key(i: int) -> str:
    return f"layer_{i}"


class MultiLayerNetwork:
    """Runtime network over a :class:`MultiLayerConfiguration`."""

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = conf.layers
        self.training = conf.training
        self.policy = _dtypes.policy_from_name(conf.training.dtype)
        self.params: Optional[Dict[str, Dict[str, jax.Array]]] = None
        self.state: Dict[str, Dict[str, jax.Array]] = {}
        self.updater_state: Optional[Pytree] = None
        self.listeners: List[Any] = []
        self.iteration_count = 0   # minibatches seen (listener-visible)
        self._update_count = 0     # parameter updates applied (tbptt chunks too)
        self.epoch_count = 0
        self._score: Optional[float] = None
        self._rnn_state: Optional[List[Dict[str, jax.Array]]] = None
        self._rnn_steps_fed = 0    # streaming steps since last cache reset
        self._updater = None
        self._jit_cache: Dict[str, Any] = {}
        # on-device training-health stats (util.health): None = off (the
        # default; the no-stats trace is untouched), a StatsConfig routes
        # fit_batch/fit_scan through the stats-collecting step variant
        self.health_stats: Optional[_health.StatsConfig] = None
        self._last_health_stats: Optional[_health.DeviceStats] = None

        out = self.layers[-1]
        self._has_loss_output = hasattr(out, "compute_score_array")

    # ------------------------------------------------------------------
    # init (parity: MultiLayerNetwork.init :368)
    # ------------------------------------------------------------------

    def init(self, key: Optional[jax.Array] = None) -> "MultiLayerNetwork":
        if key is None:
            key = _rng.key(self.training.seed)
        params, state = {}, {}
        for i, layer in enumerate(self.layers):
            lk = _rng.fold_name(key, _layer_key(i))
            params[_layer_key(i)] = layer.init_params(lk, self.policy)
            state[_layer_key(i)] = layer.init_state(self.policy)
        self.params = params
        self.state = state
        # persistent-state keys per layer (e.g. BN running stats), cached so
        # the hot fit loop never re-calls init_state just to read key names
        self._persistent_keys = [
            tuple(layer.init_state(self.policy).keys()) for layer in self.layers]
        self._updater = _updaters.make_updater(
            self.training, self._lr_multipliers())
        self.updater_state = self._updater.init(params)
        return self

    def _lr_multipliers(self) -> Pytree:
        """Static per-param LR multiplier pytree (per-layer learning_rate and
        bias_learning_rate overrides, reference conf.getLearningRateByParam)."""
        base = float(self.training.learning_rate)
        mults = {}
        for i, layer in enumerate(self.layers):
            layer_lr = layer.learning_rate if layer.learning_rate is not None else base
            bias_lr = (layer.bias_learning_rate
                       if layer.bias_learning_rate is not None else layer_lr)
            if base == 0.0:
                # frozen net: any per-layer override would be silently scaled
                # to 0 through the multiplier — reject it loudly
                if layer_lr != 0.0 or bias_lr != 0.0:
                    raise ValueError(
                        f"layer {i} sets learning_rate={layer_lr}/"
                        f"bias_learning_rate={bias_lr} but the global "
                        "learning_rate is 0.0; per-layer overrides are "
                        "expressed as multiples of the global rate")
                mults[_layer_key(i)] = {
                    name: 1.0 for name in layer.param_shapes(self.policy)}
                continue
            mults[_layer_key(i)] = {
                name: (bias_lr / base if name == "b" else layer_lr / base)
                for name in layer.param_shapes(self.policy)
            }
        return mults

    def num_params(self) -> int:
        if self.params is None:
            raise ValueError("call init() first")
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self.params))

    # ------------------------------------------------------------------
    # functional forward core
    # ------------------------------------------------------------------

    def _forward(self, params, states, x, *, train: bool, rng=None,
                 mask=None, upto: Optional[int] = None,
                 collect: bool = False):
        """Thread input through preprocessors + layers.

        Returns (activations | final activation, new_states).
        `states` is a list of per-layer dicts; recurrent carry (h/c) rides in
        the same dicts when present (TBPTT / rnnTimeStep).
        """
        upto = len(self.layers) if upto is None else upto
        if self.training.gradient_checkpointing and train and not collect:
            return self._forward_segmented(params, states, x, rng=rng,
                                           mask=mask, upto=upto)
        minibatch = x.shape[0]
        cur, cur_mask = x, mask
        acts = [x] if collect else None
        new_states = []
        for i in range(len(self.layers)):
            if i >= upto:
                new_states.append(states[i])
                continue
            lrng = None if rng is None else _rng.fold_name(rng, _layer_key(i))
            cur, cur_mask, st = self._apply_layer(
                i, params[_layer_key(i)], cur, cur_mask, states[i], lrng,
                train=train, minibatch=minibatch)
            new_states.append(st)
            if collect:
                acts.append(cur)
        return (acts if collect else cur), new_states

    def _apply_layer(self, i, p_i, cur, cur_mask, state_i, lrng, *,
                     train, minibatch):
        """Preprocessor + apply at layer position ``i`` — the single
        definition of per-layer forward semantics, shared by the plain and
        remat-segmented paths (so they cannot drift)."""
        proc = self.conf.input_preprocessors.get(i)
        if proc is not None:
            cur = call_preprocessor(proc, cur, minibatch_size=minibatch,
                                    rng=lrng)
            cur_mask = proc.transform_mask(cur_mask, minibatch_size=minibatch)
        cur, st = self.layers[i].apply(p_i, cur, state=state_i, train=train,
                                       rng=lrng, mask=cur_mask,
                                       policy=self.policy)
        return cur, cur_mask, (st if st is not None else {})

    def _forward_segmented(self, params, states, x, *, rng=None, mask=None,
                           upto: Optional[int] = None):
        """Training forward with SEGMENT-level rematerialization: layers are
        grouped into ~sqrt(N) runs and each run re-executes under
        ``jax.checkpoint`` in the backward — only segment-boundary
        activations stay live (per-layer checkpointing would keep every
        layer output as a residual and save almost nothing)."""
        n = len(self.layers) if upto is None else upto
        n_seg = max(1, int(np.ceil(np.sqrt(max(n, 1)))))
        minibatch = x.shape[0]
        cur, cur_mask = x, mask
        new_states: List[Dict] = []
        for idx in np.array_split(np.arange(n), n_seg):
            seg = [int(i) for i in idx]
            seg_params = [params[_layer_key(i)] for i in seg]
            seg_states = [states[i] for i in seg]
            seg_rngs = [None if rng is None
                        else _rng.fold_name(rng, _layer_key(i)) for i in seg]

            def seg_fn(p_seg, cur, cur_mask, st_seg, rngs, _seg=tuple(seg)):
                st_out = []
                for j, i in enumerate(_seg):
                    cur, cur_mask, st = self._apply_layer(
                        i, p_seg[j], cur, cur_mask, st_seg[j], rngs[j],
                        train=True, minibatch=minibatch)
                    st_out.append(st)
                return cur, cur_mask, st_out

            cur, cur_mask, st_out = jax.checkpoint(seg_fn)(
                seg_params, cur, cur_mask, seg_states, seg_rngs)
            new_states.extend(st_out)
        new_states.extend(states[n:])   # layers beyond upto: untouched
        return cur, new_states

    def _states_list(self, rnn_state=None):
        out = []
        for i in range(len(self.layers)):
            st = dict(self.state.get(_layer_key(i), {}))
            if rnn_state is not None and rnn_state[i]:
                st.update(rnn_state[i])
            out.append(st)
        return out

    def _persist_states(self, new_states):
        """Keep only persistent (init_state-declared) entries, e.g. BN stats."""
        for i, keys in enumerate(self._persistent_keys):
            if keys:
                self.state[_layer_key(i)] = {
                    k: new_states[i][k] for k in keys if k in new_states[i]}

    @staticmethod
    def _extract_rnn_carry(new_states):
        return [{k: v for k, v in st.items() if k in ("h", "c")}
                for st in new_states]

    # ------------------------------------------------------------------
    # inference (parity: output :1581 / feedForward :627 / rnnTimeStep :2274)
    # ------------------------------------------------------------------

    def output(self, x, train: bool = False):
        """Final-layer activations (compiled; cached per train/eval mode).
        train=True runs train-mode forward semantics (dropout active, BN
        batch statistics) without updating parameters."""
        x = jnp.asarray(x)
        # trace_env_key: flash-attention routing flags are read at trace
        # time, so the compiled program is only reused while they match
        cache_key = f"output_train={train}@{_xla.trace_env_key()}"
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            @jax.jit
            def fn(params, states, x, rng):
                out, _ = self._forward(params, states, x, train=train,
                                       rng=rng if train else None)
                return out
            fn = _xla.retrace_guard(fn, "MultiLayerNetwork.output")
            self._jit_cache[cache_key] = fn
        rng = _rng.fold_name(_rng.key(self.training.seed),
                             f"output_{self.iteration_count}") if train else None
        return fn(self.params, self._states_list(), x, rng)

    def feed_forward(self, x, train: bool = False) -> List[jax.Array]:
        """All layer activations, input first (parity: feedForward :627)."""
        x = jnp.asarray(x)
        acts, _ = self._forward(self.params, self._states_list(), x,
                                train=train, collect=True)
        return acts

    def rnn_clear_previous_state(self) -> None:
        self._rnn_state = None
        self._rnn_steps_fed = 0

    def rnn_time_step(self, x):
        """Streaming inference: feed one (or a few) timesteps, carrying h/c
        (parity: rnnTimeStep :2274). x: [b, f] or [b, t, f]."""
        x = jnp.asarray(x)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]
        if self._rnn_state is None:
            # seed the streaming carries (LSTM h/c zeros; attention K/V
            # caches when max_cache_t is set) — apply() distinguishes a
            # streaming call from plain output() by the presence of the
            # carried cache
            self._rnn_state = self._zero_rnn_carry(x.shape[0])
            self._rnn_steps_fed = 0
        # strict-mode streaming caches refuse the overflowing chunk
        # host-side, before it can touch the cache
        _precheck_streamed_steps(self, x.shape[1])
        cache_key = f"rnn_time_step@{_xla.trace_env_key()}"
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            @jax.jit
            def fn(params, states, x):
                out, new_states = self._forward(params, states, x,
                                                train=False)
                return out, self._extract_rnn_carry(new_states)
            fn = _xla.retrace_guard(fn, "MultiLayerNetwork.rnn_time_step")
            self._jit_cache[cache_key] = fn
        out, self._rnn_state = fn(self.params,
                                  self._states_list(self._rnn_state), x)
        # count only steps the cache actually absorbed (a rejected chunk
        # raised above and never touched it)
        _note_streamed_steps(self, x.shape[1])
        return out[:, 0, :] if (squeeze and out.ndim == 3) else out

    # ------------------------------------------------------------------
    # score + gradients (parity: computeGradientAndScore :1867)
    # ------------------------------------------------------------------

    def _reg_penalty(self, params):
        """l1 + 0.5*l2 penalties over each layer's regularized params
        (parity: BaseLayer.calcL1/calcL2; gradient of 0.5*l2*||W||^2 is l2*W,
        matching the reference's update)."""
        if not self.training.regularization:
            return 0.0
        acc_dtype = (jnp.float64 if self.policy.param_dtype == jnp.float64
                     else jnp.float32)
        total = 0.0
        for i, layer in enumerate(self.layers):
            l1 = float(layer.l1 or 0.0)
            l2 = float(layer.l2 or 0.0)
            if l1 == 0.0 and l2 == 0.0:
                continue
            lp = params[_layer_key(i)]
            for name in layer.regularized_params():
                if name not in lp:
                    continue
                w = lp[name].astype(acc_dtype)
                if l1:
                    total = total + l1 * jnp.sum(jnp.abs(w))
                if l2:
                    total = total + 0.5 * l2 * jnp.sum(jnp.square(w))
        return total

    def _loss_fn(self, params, states, x, y, mask, rng, *,
                 collect_stats=False):
        # collect_stats: falsy = plain loss; True or a health.StatsConfig
        # (whose act_sample bounds the activation reductions) additionally
        # returns per-layer activation summaries through the aux output
        if not self._has_loss_output:
            raise ValueError(
                "final layer has no loss (need OutputLayer/RnnOutputLayer/"
                "LossLayer to train with fit())")
        n_hidden = len(self.layers) - 1
        fwd = self._forward(
            params, states, x, train=True, rng=rng, mask=mask,
            upto=n_hidden, collect=collect_stats)
        if collect_stats:
            # collect=True keeps per-layer activations (bypassing remat —
            # stats collection trades that memory saving for visibility);
            # summarize each to 3 gradient-stopped scalars right here
            acts, new_states = fwd
            hidden = acts[-1]
            sample = getattr(collect_stats, "act_sample", 0)
            act_stats = {
                _layer_key(i): _health.act_summary(acts[i + 1], sample)
                for i in range(n_hidden)}
        else:
            hidden, new_states = fwd
        out_idx = len(self.layers) - 1
        out_layer = self.layers[out_idx]
        proc = self.conf.input_preprocessors.get(out_idx)
        out_mask = mask
        if proc is not None:
            lrng = None if rng is None else _rng.fold_name(rng,
                                                           _layer_key(out_idx))
            hidden = call_preprocessor(proc, hidden,
                                       minibatch_size=x.shape[0], rng=lrng)
            out_mask = proc.transform_mask(out_mask, minibatch_size=x.shape[0])
        score_arr = out_layer.compute_score_array(
            params[_layer_key(out_idx)], hidden, y, mask=out_mask,
            policy=self.policy)
        denom = _losses.masked_denominator(
            out_mask, y, score_arr.shape[0],
            sparse=_losses.is_sparse(out_layer.loss))
        loss = jnp.sum(score_arr) / denom
        loss = loss + self._reg_penalty(params)
        # layers may surface auxiliary objectives through their state
        # (e.g. MoELayer's load-balancing loss, pre-scaled by aux_weight)
        for st in new_states:
            if "aux_loss" in st:
                loss = loss + st["aux_loss"]
        # keep full precision under a float64 policy (gradient checking);
        # float32 otherwise (bf16 losses are too coarse for LR-sized steps)
        loss_dtype = (jnp.float64 if self.policy.param_dtype == jnp.float64
                      else jnp.float32)
        if collect_stats:
            return loss.astype(loss_dtype), (new_states, act_stats)
        return loss.astype(loss_dtype), new_states

    def score_for(self, x, y, mask=None) -> float:
        """Loss on a batch without updating (parity: score via
        computeGradientAndScore, eval mode)."""
        x, y = jnp.asarray(x), jnp.asarray(y)
        loss, _ = self._loss_fn(self.params, self._states_list(), x, y,
                                mask, None)
        return float(loss)

    def score(self) -> Optional[float]:
        """Score from the most recent fit iteration (parity: score() :1900).
        Lazily syncs: the fit loop keeps the loss on device so step dispatch
        pipelines; the device→host transfer happens here, on demand."""
        if self._score is None:
            return None
        self._score = float(self._score)
        return self._score

    def compute_gradient_and_score(self, x, y, mask=None):
        """(gradients, score) for one batch — no update applied."""
        x, y = jnp.asarray(x), jnp.asarray(y)
        (loss, _), grads = jax.value_and_grad(
            self._loss_fn, has_aux=True)(
                self.params, self._states_list(), x, y, mask, None)
        return grads, float(loss)

    # ------------------------------------------------------------------
    # the jitted train step
    # ------------------------------------------------------------------

    def _make_train_step(self, stats_cfg: Optional[_health.StatsConfig] = None):
        t = self.training
        norm_kind = t.gradient_normalization
        norm_thr = float(t.gradient_normalization_threshold)
        updater = self._updater
        collect = stats_cfg is not None

        def step(params, opt_state, states, x, y, mask, rng, iteration):
            loss, new_states, grads_raw, act_stats = \
                _health.value_grad_with_stats(
                    self._loss_fn, stats_cfg, params, states, x, y, mask, rng)
            grads = _updaters.normalize_gradients(grads_raw, norm_kind,
                                                  norm_thr)
            deltas, opt_state = updater.update(grads, opt_state, iteration)
            params = _updaters.apply_updates(params, deltas)
            if not collect:
                return params, opt_state, new_states, loss
            # per-layer health stats in the SAME dispatch: raw (pre-norm)
            # grads, the applied deltas, and the post-update params
            stats = _health.model_stats(params, grads_raw, deltas,
                                        act_stats, stats_cfg, loss=loss)
            return params, opt_state, new_states, loss, stats

        return jax.jit(step, donate_argnums=(0, 1),
                       compiler_options=_xla.train_step_options())

    def _train_step(self):
        # explicit override first (ParallelWrapper installs its sharded
        # SPMD step here; an override is pinned, not trace-env-keyed and
        # not stats-keyed — sharded steps do not collect health stats)
        fn = self._jit_cache.get("train_step_override")
        if fn is not None:
            return fn
        cfg = self.health_stats
        suffix = "" if cfg is None else f"|stats={cfg.trace_key()}"
        cache_key = f"train_step@{_xla.trace_env_key()}{suffix}"
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            # distinct guard name for the stats variant: the no-stats
            # trace's retrace pin (1 compile per signature) must not
            # move when stats are toggled on and back off
            name = ("MultiLayerNetwork.train_step" if cfg is None
                    else "MultiLayerNetwork.train_step_stats")
            fn = _xla.retrace_guard(self._make_train_step(cfg), name)
            self._jit_cache[cache_key] = fn
        return fn

    def _make_train_scan(self, stats_cfg: Optional[_health.StatsConfig] = None):
        """K train steps fused into ONE XLA program via lax.scan — the
        idiomatic TPU inner loop: no per-step host dispatch, the whole
        sequence of updates runs on-chip. Used by fit_scan(). With
        ``stats_cfg`` the scan also emits the health-stats pytree of the
        LAST step (stats stay per-dispatch-window, like the score)."""
        t = self.training
        norm_kind = t.gradient_normalization
        norm_thr = float(t.gradient_normalization_threshold)
        updater = self._updater
        base = _rng.key(t.seed)
        collect = stats_cfg is not None

        def one(carry, batch):
            params, opt_state, states, it = carry
            x, y, mask = batch
            # per-step rng derived from the TRACED counter — computing keys
            # eagerly from the host-side update count bakes fresh constants
            # into the program and forces a recompile every call
            rng = jax.random.fold_in(base, it)
            loss, new_states, grads_raw, act_stats = \
                _health.value_grad_with_stats(
                    self._loss_fn, stats_cfg, params, states, x, y, mask, rng)
            grads = _updaters.normalize_gradients(grads_raw, norm_kind,
                                                  norm_thr)
            deltas, opt_state = updater.update(grads, opt_state, it)
            params = _updaters.apply_updates(params, deltas)
            # carry structure must stay fixed: keep exactly the persistent
            # state keys (BN stats); transient rnn carry (h/c) resets per batch
            kept = [
                {k: new_states[i].get(k, v) for k, v in st_old.items()}
                for i, st_old in enumerate(states)]
            if collect:
                stats = _health.model_stats(params, grads_raw, deltas,
                                            act_stats, stats_cfg, loss=loss)
                return (params, opt_state, kept, it + 1), (loss, stats)
            return (params, opt_state, kept, it + 1), loss

        def scan_steps(params, opt_state, states, xs, ys, masks, it0):
            (params, opt_state, states, _), ys_out = jax.lax.scan(
                one, (params, opt_state, states, it0), (xs, ys, masks),
                unroll=_xla.scan_unroll())
            if collect:
                losses, stats_seq = ys_out
                last_stats = jax.tree_util.tree_map(lambda a: a[-1],
                                                    stats_seq)
                return params, opt_state, states, losses, last_stats
            return params, opt_state, states, ys_out

        return jax.jit(scan_steps, donate_argnums=(0, 1),
                       compiler_options=_xla.train_step_options())

    def fit_scan(self, xs, ys, masks=None):
        """Train on K pre-staged batches in one device dispatch.

        xs: [k, b, ...], ys: [k, b, ...], masks: optional [k, ...].
        Returns the per-step losses (device array, shape [k]).
        """
        xs, ys = jnp.asarray(xs), jnp.asarray(ys)
        self._reject_tbptt(xs[0], "fit_scan")
        k = xs.shape[0]
        if masks is not None:
            masks = jnp.asarray(masks)
        cfg = self.health_stats
        suffix = "" if cfg is None else f"|stats={cfg.trace_key()}"
        cache_key = f"train_scan@{_xla.trace_env_key()}{suffix}"
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            name = ("MultiLayerNetwork.train_scan" if cfg is None
                    else "MultiLayerNetwork.train_scan_stats")
            fn = _xla.retrace_guard(self._make_train_scan(cfg), name)
            self._jit_cache[cache_key] = fn
        it0 = jnp.asarray(self._update_count, jnp.int32)
        states = self._states_list()
        out = fn(
            self.params, self.updater_state, states, xs, ys, masks, it0)
        if cfg is not None:
            params, opt_state, new_states, losses, stats = out
            self._last_health_stats = _health.DeviceStats(
                stats, iteration=self.iteration_count + k,
                model="MultiLayerNetwork")
        else:
            params, opt_state, new_states, losses = out
        self.params = params
        self.updater_state = opt_state
        self._update_count += k
        self._persist_states(new_states)
        self._score = losses[-1]
        # replay per-step losses so listener/stats semantics (score history,
        # throughput via record_batch) match fit()/fit_batch for k updates
        if self.listeners:
            batch_size = int(xs.shape[1])
            per_step = np.asarray(losses)
            for i in range(k):
                self._fire_iteration(batch_size, per_step[i])
        else:
            self.iteration_count += k
        return losses

    def _make_train_repeat(self, stats_cfg: Optional[_health.StatsConfig] = None):
        """K train steps on ONE closed-over batch via lax.scan over step
        indices — constant HBM regardless of K. Used by fit_repeated().
        With ``stats_cfg`` the scan also emits the health-stats pytree of
        the LAST step (same window semantics as fit_scan)."""
        t = self.training
        norm_kind = t.gradient_normalization
        norm_thr = float(t.gradient_normalization_threshold)
        updater = self._updater
        base = _rng.key(t.seed)
        collect = stats_cfg is not None

        def one(x, y, mask, carry, it):
            params, opt_state, states = carry
            rng = jax.random.fold_in(base, it)
            loss, new_states, grads_raw, act_stats = \
                _health.value_grad_with_stats(
                    self._loss_fn, stats_cfg, params, states, x, y, mask, rng)
            grads = _updaters.normalize_gradients(grads_raw, norm_kind,
                                                  norm_thr)
            deltas, opt_state = updater.update(grads, opt_state, it)
            params = _updaters.apply_updates(params, deltas)
            kept = [
                {k: new_states[i].get(k, v) for k, v in st_old.items()}
                for i, st_old in enumerate(states)]
            if collect:
                stats = _health.model_stats(params, grads_raw, deltas,
                                            act_stats, stats_cfg, loss=loss)
                return (params, opt_state, kept), (loss, stats)
            return (params, opt_state, kept), loss

        def repeat_steps(params, opt_state, states, x, y, mask, it0, k):
            # unroll (default 2): XLA removes inter-iteration carry copies
            # between the paired bodies (measured ~1.2 ms/step on ResNet-50
            # @ v5e); DL4JTPU_SCAN_UNROLL overrides for tuning
            (params, opt_state, states), ys_out = jax.lax.scan(
                functools.partial(one, x, y, mask), (params, opt_state, states),
                it0 + jnp.arange(k), unroll=_xla.scan_unroll())
            if collect:
                losses, stats_seq = ys_out
                last_stats = jax.tree_util.tree_map(lambda a: a[-1],
                                                    stats_seq)
                return params, opt_state, states, losses, last_stats
            return params, opt_state, states, ys_out

        return jax.jit(repeat_steps, donate_argnums=(0, 1, 2),
                       static_argnums=(7,),
                       compiler_options=_xla.train_step_options())

    def fit_repeated(self, x, y, k: int, mask=None):
        """Run K optimizer updates on one pre-staged batch in a single device
        dispatch (lax.scan over step indices). The on-chip analog of calling
        ``fit_batch(x, y)`` K times: same per-update rng folding, iteration
        counters, and listener firing — but one dispatch and one batch of HBM.
        Used for steady-state throughput measurement; returns [k] losses."""
        x, y = jnp.asarray(x), jnp.asarray(y)
        self._reject_tbptt(x, "fit_repeated")
        if mask is not None:
            mask = jnp.asarray(mask)
        cfg = self.health_stats
        suffix = "" if cfg is None else f"|stats={cfg.trace_key()}"
        cache_key = f"train_repeat@{_xla.trace_env_key()}{suffix}"
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            name = ("MultiLayerNetwork.train_repeat" if cfg is None
                    else "MultiLayerNetwork.train_repeat_stats")
            fn = _xla.retrace_guard(self._make_train_repeat(cfg), name)
            self._jit_cache[cache_key] = fn
        it0 = jnp.asarray(self._update_count, jnp.int32)
        out = fn(
            self.params, self.updater_state, self._states_list(), x, y,
            mask, it0, int(k))
        if cfg is not None:
            params, opt_state, new_states, losses, stats = out
            self._last_health_stats = _health.DeviceStats(
                stats, iteration=self.iteration_count + int(k),
                model="MultiLayerNetwork")
        else:
            params, opt_state, new_states, losses = out
        self.params = params
        self.updater_state = opt_state
        self._update_count += int(k)
        self._persist_states(new_states)
        self._score = losses[-1]
        if self.listeners:
            batch_size = int(x.shape[0])
            per_step = np.asarray(losses)
            for i in range(int(k)):
                self._fire_iteration(batch_size, per_step[i])
        else:
            self.iteration_count += int(k)
        return losses

    # ------------------------------------------------------------------
    # fit (parity: fit(DataSetIterator) :1037, doTruncatedBPTT :1079)
    # ------------------------------------------------------------------

    def set_listeners(self, *listeners) -> None:
        # Accept both varargs and a single collection (ref Model.setListeners
        # has both overloads).
        if len(listeners) == 1 and isinstance(listeners[0], (list, tuple)):
            listeners = tuple(listeners[0])
        self.listeners = list(listeners)

    def add_listener(self, listener) -> None:
        self.listeners.append(listener)

    def enable_health_stats(self, config=True) -> None:
        """Compute per-layer training-health stats (util.health) INSIDE
        the train dispatch from the next fit call on: the stats-keyed jit
        cache traces a separate program, so the cached no-stats trace is
        untouched and toggling back off reuses it without a recompile.
        Consumers read :func:`util.health.latest_stats` — one host sync
        per read, the snapshot carries the step loss."""
        self.health_stats = _health.StatsConfig.coerce(config)

    def disable_health_stats(self) -> None:
        self.health_stats = None

    def fit(self, data, labels=None, *, epochs: int = 1, mask=None,
            coalesce: Optional[int] = None, session=None) -> None:
        """Train. `data` may be:
          - (features, labels) arrays (`labels=None` form passes labels here),
          - a DataSet (has .features/.labels),
          - an iterator yielding DataSets or (features, labels) tuples.

        The loop is dispatch-asynchronous: host batches are device-staged
        by a background thread (``util.ingest.stage``; ``DL4JTPU_INGEST=0``
        disables), losses stay on device behind a bounded in-flight window
        (``DL4JTPU_MAX_INFLIGHT``), and listeners receive a ``LazyScore``
        that syncs only when read. ``coalesce=K`` (or ``DL4JTPU_COALESCE_K``)
        additionally fuses runs of K same-shape batches into one fit_scan
        dispatch — opt-in, because the fused path derives per-step rng
        differently. Epoch resets happen lazily at the START of each
        subsequent epoch, so the final epoch never restarts the producer
        just to throw the work away. ``session`` attaches a
        ``util.durable.DurableSession`` (cursor tracking, async
        checkpoints, preemption drain, watchdog).
        """
        from ..util.ingest import run_fit_loop
        if self.params is None:
            self.init()
        run_fit_loop(self, data, labels, mask, epochs, coalesce,
                     model_label="MultiLayerNetwork", session=session)

    @staticmethod
    def _as_batches(data, labels=None, mask=None):
        from ..util.batching import iter_batches
        return iter_batches(data, labels, mask)

    def fit_batch(self, x, y, mask=None) -> float:
        """One minibatch update (tbptt-aware). Returns the score."""
        x, y = jnp.asarray(x), jnp.asarray(y)
        if mask is not None:
            mask = jnp.asarray(mask)
        if (self.conf.backprop_type == "truncated_bptt" and x.ndim == 3
                and x.shape[1] > self.conf.tbptt_fwd_length):
            return self._fit_tbptt(x, y, mask)
        loss = self._step_and_update(x, y, mask, rnn_state=None)
        self._fire_iteration(x.shape[0], loss)
        return loss

    def _reject_tbptt(self, x, api: str) -> None:
        """The fused-scan paths run ONE full-sequence BPTT update per batch;
        silently doing that under a truncated_bptt config would change both
        memory behavior and optimization semantics — refuse loudly."""
        if (self.conf.backprop_type == "truncated_bptt" and x.ndim == 3
                and x.shape[1] > self.conf.tbptt_fwd_length):
            raise ValueError(
                f"{api} does not chunk truncated BPTT (T={x.shape[1]} > "
                f"tbptt_fwd_length={self.conf.tbptt_fwd_length}); use "
                "fit()/fit_batch(), or pre-chunk the sequences")

    def _fit_tbptt(self, x, y, mask) -> float:
        """Truncated BPTT: slice [b,t,..] into fwd-length chunks, carrying
        recurrent state across chunks with gradients stopped at the boundary
        (parity: doTruncatedBPTT :1079)."""
        length = self.conf.tbptt_fwd_length
        T = x.shape[1]
        rnn_state = self._zero_rnn_carry(x.shape[0])
        loss = 0.0
        for start in range(0, T, length):
            end = min(start + length, T)
            xs = x[:, start:end]
            ys = y[:, start:end] if y.ndim == 3 else y
            ms = mask[:, start:end] if (mask is not None and mask.ndim >= 2) else mask
            loss = self._step_and_update(xs, ys, ms, rnn_state=rnn_state)
            rnn_state = self._last_rnn_carry
            # one iteration (and listener firing) per TBPTT segment, same as
            # the graph runtime and the reference's doTruncatedBPTT
            self._fire_iteration(x.shape[0], loss)
        return loss

    def _zero_rnn_carry(self, batch):
        carry = []
        for layer in self.layers:
            # max_cache_t None = a streaming-capable layer (attention)
            # whose cache is disabled — it carries nothing
            if (hasattr(layer, "_zero_state")
                    and getattr(layer, "max_cache_t", True) is not None):
                h, c = layer._zero_state(batch, self.policy)
                carry.append({"h": h, "c": c})
            else:
                carry.append({})
        return carry

    def _step_and_update(self, x, y, mask, rnn_state) -> float:
        # keyed on the update counter so each tbptt chunk gets a fresh dropout
        # stream and the updater sees a monotonically advancing step
        rng = _rng.fold_name(_rng.key(self.training.seed),
                             f"update_{self._update_count}")
        states = self._states_list(rnn_state)
        it = jnp.asarray(self._update_count, jnp.int32)
        out = self._train_step()(
            self.params, self.updater_state, states, x, y, mask, rng, it)
        # sharded overrides always return 4 outputs; only the stats
        # variant of the owned step returns the fifth (the stats pytree)
        if len(out) == 5:
            params, opt_state, new_states, loss, stats = out
            self._last_health_stats = _health.DeviceStats(
                stats, iteration=self.iteration_count + 1,
                model="MultiLayerNetwork")
        else:
            params, opt_state, new_states, loss = out
        self.params = params
        self.updater_state = opt_state
        self._update_count += 1
        # stop-gradient boundary for tbptt: carry values, not graph
        self._last_rnn_carry = jax.tree_util.tree_map(
            jax.lax.stop_gradient, self._extract_rnn_carry(new_states))
        self._persist_states(new_states)
        # keep the loss on device — no host sync in the hot loop; score()
        # and listeners that read it pay the transfer lazily
        self._score = loss
        return loss

    def _fire_iteration(self, batch_size, loss):
        self.iteration_count += 1
        if not self.listeners:
            return
        # listeners get a LazyScore: the device loss syncs to host only
        # when (and if) a listener actually reads it — frequency-gated
        # listeners pay one sync per window, silent ones pay zero
        from ..util.ingest import as_listener_score
        score = as_listener_score(loss)
        for l in self.listeners:
            if hasattr(l, "record_batch"):
                l.record_batch(batch_size)
            l.iteration_done(self, self.iteration_count, score)

    # ------------------------------------------------------------------
    # layerwise pretraining (parity: MultiLayerNetwork.pretrain :1052 —
    # greedy per-layer AutoEncoder reconstruction / RBM CD-k before backprop)
    # ------------------------------------------------------------------

    def pretrain(self, data, labels=None, *, epochs: int = 1,
                 learning_rate: Optional[float] = None) -> None:
        """Greedy layerwise pretraining of AutoEncoder/RBM layers. Each
        pretrainable layer trains on the previous layers' activations
        (earlier layers frozen), then the stack moves one layer deeper."""
        if self.params is None:
            self.init()
        lr = float(learning_rate if learning_rate is not None
                   else self.training.learning_rate)
        pre_idx = [i for i, l in enumerate(self.layers)
                   if hasattr(l, "pretrain_loss")
                   or hasattr(l, "contrastive_divergence_grads")]
        if not pre_idx:
            return
        from .conf.pretrain import make_pretrain_step
        batches = list(self._as_batches(data, labels, None))
        for i in pre_idx:
            step = make_pretrain_step(self.layers[i], lr, self.policy)
            # earlier layers are frozen while layer i trains, so its input
            # activations are constant across epochs — but materializing all
            # of them is O(dataset) device memory, so only precompute when
            # the reuse (epochs>1) and the footprint (few batches) justify it
            cache_all = epochs > 1 and len(batches) <= 64
            hiddens = ([self._activation_upto(jnp.asarray(x), i)
                        for x, _, _ in batches] if cache_all else None)
            for e in range(epochs):
                for bi, (x, _, _) in enumerate(batches):
                    hidden = (hiddens[bi] if cache_all
                              else self._activation_upto(jnp.asarray(x), i))
                    rng = _rng.fold_name(
                        _rng.key(self.training.seed), f"pre_{i}_{e}_{bi}")
                    self.params[_layer_key(i)] = step(
                        self.params[_layer_key(i)], hidden, rng)

    def _activation_upto(self, x, layer_idx: int):
        """Input activations for layer `layer_idx` (frozen earlier layers)."""
        # trace_env_key: frozen-layer forwards trace the same attention
        # routing flags as output()/fit — a flag flip must retrace here too
        fn_key = f"acts_upto_{layer_idx}@{_xla.trace_env_key()}"
        fn = self._jit_cache.get(fn_key)
        if fn is None:
            @jax.jit
            def fn(params, states, x):
                cur, cur_mask = x, None
                minibatch = x.shape[0]
                for j in range(layer_idx):
                    proc = self.conf.input_preprocessors.get(j)
                    if proc is not None:
                        cur = proc(cur, minibatch_size=minibatch)
                    cur, _ = self.layers[j].apply(
                        params[_layer_key(j)], cur, state=states[j],
                        train=False, policy=self.policy)
                proc = self.conf.input_preprocessors.get(layer_idx)
                if proc is not None:
                    cur = proc(cur, minibatch_size=minibatch)
                return cur
            self._jit_cache[fn_key] = fn
        return fn(self.params, self._states_list(), x)


    # ------------------------------------------------------------------
    # evaluation bridge (full Evaluation class in eval/)
    # ------------------------------------------------------------------

    def evaluate(self, data, labels=None):
        """Classification evaluation over an iterator or (x, y) arrays.

        When the iterator yields DataSets carrying ``example_metadata``
        (``RecordReaderDataSetIterator(collect_metadata=True)``), the
        provenance flows into the returned Evaluation — ask it
        ``get_prediction_errors()`` for WHICH source records were
        misclassified (parity: ``Evaluation.java:195`` eval-with-metadata
        driven from the iterator)."""
        from ..eval import Evaluation
        from ..util.batching import iter_batches
        ev = Evaluation()
        # fit() no longer resets the source after its final epoch; revive
        # an exhausted resettable iterator here instead of silently
        # evaluating zero batches
        if (hasattr(data, "has_next") and not data.has_next()
                and hasattr(data, "reset")):
            data.reset()
        for x, y, m, meta in iter_batches(data, labels, with_meta=True):
            out = self.output(jnp.asarray(x))
            ev.eval(np.asarray(y), np.asarray(out),
                    mask=None if m is None else np.asarray(m),
                    metadata=meta)
        if hasattr(data, "reset"):
            data.reset()
        return ev

    # ------------------------------------------------------------------
    # serde bridge (full checkpoint container in util/serialization.py)
    # ------------------------------------------------------------------

    def clone_params(self):
        """Deep copy — the train step donates the live param buffers, so an
        aliasing 'clone' would be invalidated by the next fit_batch."""
        return jax.tree_util.tree_map(lambda p: jnp.array(p), self.params)

    def set_params(self, params) -> None:
        self.params = params
