"""ComputationGraph: the DAG runtime model.

Parity: reference ``nn/graph/ComputationGraph.java`` — ``init`` (``:278``,
topo sort + params), ``fit`` (``:614-760``), ``computeGradientAndScore``
(``:912``), forward over ``topologicalOrder`` (``:1007``), ``output``
(``:1058``); multi-input/multi-output, loss summed over all output layers.

TPU-native design: the whole topo-ordered DAG forward + loss + ``jax.grad``
backward + updater apply traces into ONE jitted XLA program (donated params).
The reference's per-vertex ``doForward``/``doBackward`` dispatch loop has no
runtime analog — vertex boundaries disappear into XLA fusion.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes as _dtypes
from .. import losses as _losses
from .. import rng as _rng
from ..optimize import updaters as _updaters
from ..util import health as _health
from ..util import xla as _xla
from ..util.netutil import note_streamed_steps as _note_streamed_steps
from ..util.netutil import precheck_streamed_steps as _precheck_streamed_steps
from .conf.graph import ComputationGraphConfiguration, LayerVertex
from .conf.preprocessors import call_preprocessor

Pytree = Any


def _as_list(v) -> List[Any]:
    return list(v) if isinstance(v, (list, tuple)) else [v]


class ComputationGraph:
    """Runtime DAG network over a :class:`ComputationGraphConfiguration`."""

    def __init__(self, conf: ComputationGraphConfiguration):
        conf.validate()
        self.conf = conf
        self.training = conf.training
        self.policy = _dtypes.policy_from_name(conf.training.dtype)
        self.topo_order = conf.topological_order()
        self.params: Optional[Dict[str, Dict[str, jax.Array]]] = None
        self.state: Dict[str, Dict[str, jax.Array]] = {}
        self.updater_state: Optional[Pytree] = None
        self.listeners: List[Any] = []
        self.iteration_count = 0
        self._update_count = 0
        self.epoch_count = 0
        self._score = None
        self._updater = None
        self._rnn_state: Optional[Dict[str, Dict[str, jax.Array]]] = None
        self._rnn_steps_fed = 0    # streaming steps since last cache reset
        self._jit_cache: Dict[str, Any] = {}
        # on-device training-health stats (util.health): None = off (the
        # default; the no-stats trace is untouched), a StatsConfig routes
        # fit_batch/fit_scan through the stats-collecting step variant
        self.health_stats: Optional[_health.StatsConfig] = None
        self._last_health_stats: Optional[_health.DeviceStats] = None

        self._output_layer_names = [
            n for n in conf.network_outputs
            if hasattr(self._vertex_layer(n), "compute_score_array")]

    def _vertex_layer(self, name: str):
        v = self.conf.vertices[name]
        return v.layer if isinstance(v, LayerVertex) else None

    # ------------------------------------------------------------------
    # init (parity: ComputationGraph.init :278)
    # ------------------------------------------------------------------

    def init(self, key: Optional[jax.Array] = None) -> "ComputationGraph":
        if key is None:
            key = _rng.key(self.training.seed)
        params, state = {}, {}
        for name in self.topo_order:
            v = self.conf.vertices[name]
            vk = _rng.fold_name(key, name)
            params[name] = v.init_params(vk, self.policy)
            state[name] = v.init_state(self.policy)
        self.params = params
        self.state = state
        self._persistent_keys = {
            name: tuple(self.conf.vertices[name].init_state(self.policy).keys())
            for name in self.topo_order}
        self._updater = _updaters.make_updater(
            self.training, self._lr_multipliers())
        self.updater_state = self._updater.init(params)
        return self

    def _lr_multipliers(self) -> Pytree:
        base = float(self.training.learning_rate)
        mults = {}
        for name in self.topo_order:
            v = self.conf.vertices[name]
            layer = v.layer if isinstance(v, LayerVertex) else None
            shapes = v.param_shapes(self.policy)
            if layer is None or not shapes:
                mults[name] = {k: 1.0 for k in shapes}
                continue
            layer_lr = (layer.learning_rate
                        if layer.learning_rate is not None else base)
            bias_lr = (layer.bias_learning_rate
                       if layer.bias_learning_rate is not None else layer_lr)
            if base == 0.0:
                if layer_lr != 0.0 or bias_lr != 0.0:
                    raise ValueError(
                        f"vertex {name!r} sets a per-layer learning rate but "
                        "the global learning_rate is 0.0")
                mults[name] = {k: 1.0 for k in shapes}
            else:
                mults[name] = {k: (bias_lr / base if k == "b" else layer_lr / base)
                               for k in shapes}
        return mults

    def num_params(self) -> int:
        if self.params is None:
            raise ValueError("call init() first")
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self.params))

    # ------------------------------------------------------------------
    # functional forward over the DAG
    # ------------------------------------------------------------------

    def _states_map(self, rnn_state=None) -> Dict[str, Dict[str, jax.Array]]:
        out = {}
        for n in self.topo_order:
            st = dict(self.state.get(n, {}))
            if rnn_state is not None and rnn_state.get(n):
                st.update(rnn_state[n])
            out[n] = st
        return out

    def _persist_states(self, new_states: Dict[str, Dict[str, jax.Array]]) -> None:
        for name, keys in self._persistent_keys.items():
            if keys:
                self.state[name] = {
                    k: new_states[name][k] for k in keys if k in new_states[name]}

    def _minibatch_map(self, batch: int) -> Dict[str, int]:
        """True EXAMPLE count at every vertex (batch-axis vertices like
        Stack/Unstack change it; time-flattening does not). Host-side ints,
        cached per input batch size."""
        cache = self._jit_cache.setdefault("_mb_maps", {})
        mbs = cache.get(batch)
        if mbs is None:
            mbs = {n: batch for n in self.conf.network_inputs}
            for name in self.topo_order:
                mbs[name] = self.conf.vertices[name].output_minibatch(
                    [mbs[i] for i in self.conf.vertex_inputs[name]])
            cache[batch] = mbs
        return mbs

    def _forward(self, params, states, inputs: List[jax.Array], *,
                 train: bool, rng=None, masks=None):
        """Walk the topo order; returns ({vertex: activation}, new_states)."""
        mbs = self._minibatch_map(inputs[0].shape[0])
        acts: Dict[str, jax.Array] = dict(zip(self.conf.network_inputs, inputs))
        mask_map: Dict[str, Optional[jax.Array]] = dict(
            zip(self.conf.network_inputs,
                masks if masks is not None else [None] * len(inputs)))
        new_states: Dict[str, Dict[str, jax.Array]] = {}
        for name in self.topo_order:
            in_names = self.conf.vertex_inputs[name]
            in_masks = [mask_map.get(i) for i in in_names]
            vrng = None if rng is None else _rng.fold_name(rng, name)
            out, st = self._apply_vertex(name, params[name], acts,
                                         states[name], vrng, train=train,
                                         in_masks=in_masks,
                                         minibatch=mbs[in_names[0]])
            acts[name] = out
            mask_map[name] = self.conf.vertices[name].output_mask(
                in_masks, minibatch=acts[in_names[0]].shape[0])
            new_states[name] = st
        return acts, new_states

    def _apply_vertex(self, name, params_n, local_acts, state_n, vrng, *,
                      train, in_masks=None, minibatch=None):
        """Gather inputs + apply for one vertex — the single definition of
        per-vertex forward semantics, shared by the plain and
        remat-segmented paths (so they cannot drift). ``minibatch`` is the
        NETWORK batch size (time-flattened activations make x.shape[0]
        wrong for shape-rebuilding preprocessors)."""
        v = self.conf.vertices[name]
        xs = [local_acts[i] for i in self.conf.vertex_inputs[name]]
        if in_masks is None:
            in_masks = [None] * len(xs)
        out, st = v.apply(params_n, xs, state=state_n, train=train,
                          rng=vrng, masks=in_masks, policy=self.policy,
                          minibatch=minibatch)
        return out, (st if st is not None else {})

    def _segment_plan(self):
        """Partition the topo order into ~sqrt(V) segments and, per segment,
        record which activations cross its boundary. Cached — the plan is
        pure graph structure."""
        plan = getattr(self, "_seg_plan", None)
        if plan is not None:
            return plan
        order = self.topo_order
        n_seg = max(1, int(np.ceil(np.sqrt(len(order)))))
        bounds = np.array_split(np.arange(len(order)), n_seg)
        pos = {name: i for i, name in enumerate(order)}
        # the loss head reads the output-layer vertices' INPUTS (hidden
        # activations feed compute_score_array), so those must be published
        # as segment boundaries; output-layer vertices nothing downstream
        # consumes are skipped entirely (their activation is never read —
        # same rule as the unsegmented loss walk)
        consumed = {i for ins in self.conf.vertex_inputs.values()
                    for i in ins}
        skip = {n for n in self._output_layer_names if n not in consumed}
        required = set(self.conf.network_outputs) - skip
        for name in self._output_layer_names:
            required.update(self.conf.vertex_inputs[name])
        segments = []
        for idx in bounds:
            seg = [order[i] for i in idx if order[i] not in skip]
            if not seg:
                continue
            seg_set = set(seg)
            ext_in, seen = [], set()
            for vname in seg:
                for src in self.conf.vertex_inputs[vname]:
                    if src not in seg_set and src not in seen:
                        seen.add(src)
                        ext_in.append(src)
            last = pos[seg[-1]]
            outs = [vname for vname in seg
                    if vname in required
                    or any(pos[w] > last
                           for w in order
                           if vname in self.conf.vertex_inputs[w])]
            segments.append((seg, ext_in, outs))
        self._seg_plan = (segments, skip)
        return self._seg_plan

    def _forward_segmented(self, params, states, inputs: List[jax.Array],
                           *, rng=None):
        """Training forward with segment-level rematerialization: only
        segment-boundary activations stay live for the backward pass; each
        segment's interior (conv pre-activations, BN intermediates, ...) is
        recomputed under ``jax.checkpoint``. ~sqrt(V) segments — the
        standard memory/compute trade (brief: jax.checkpoint for HBM).
        Masked inputs fall back to the unsegmented path (mask plumbing is
        host-side Python, incompatible with a traced segment boundary)."""
        mbs = self._minibatch_map(inputs[0].shape[0])
        acts: Dict[str, jax.Array] = dict(
            zip(self.conf.network_inputs, inputs))
        segments, skip = self._segment_plan()
        # skipped (unconsumed) output-layer vertices still need a state
        # entry: downstream carry structures index every vertex name
        new_states: Dict[str, Dict[str, jax.Array]] = {n: {} for n in skip}
        for seg, ext_in, outs_needed in segments:
            seg_params = {n: params[n] for n in seg}
            seg_states = {n: states[n] for n in seg}
            seg_rngs = {n: (None if rng is None else _rng.fold_name(rng, n))
                        for n in seg}

            def seg_fn(p, ext_acts, st, rngs, _seg=tuple(seg),
                       _ext=tuple(ext_in), _outs=tuple(outs_needed)):
                local = dict(zip(_ext, ext_acts))
                st_out = {}
                for vname in _seg:
                    out, vst = self._apply_vertex(
                        vname, p[vname], local, st[vname], rngs[vname],
                        train=True,
                        minibatch=mbs[self.conf.vertex_inputs[vname][0]])
                    local[vname] = out
                    st_out[vname] = vst
                return [local[o] for o in _outs], st_out

            outs, seg_new = jax.checkpoint(seg_fn)(
                seg_params, [acts[n] for n in ext_in], seg_states, seg_rngs)
            acts.update(zip(outs_needed, outs))
            new_states.update(seg_new)
        return acts, new_states

    # ------------------------------------------------------------------
    # inference (parity: output :1058)
    # ------------------------------------------------------------------

    def output(self, *inputs, train: bool = False):
        """Activations of the network outputs. Returns a single array when
        there is one output, else a list."""
        inputs = [jnp.asarray(x) for x in _as_list(
            inputs[0] if len(inputs) == 1 and isinstance(inputs[0], (list, tuple))
            else list(inputs))]
        # trace_env_key: flash-attention routing flags are read at trace
        # time, so the compiled program is only reused while they match
        cache_key = f"output_train={train}@{_xla.trace_env_key()}"
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            @jax.jit
            def fn(params, states, inputs, rng):
                acts, _ = self._forward(params, states, inputs,
                                        train=train,
                                        rng=rng if train else None)
                return [acts[n] for n in self.conf.network_outputs]
            fn = _xla.retrace_guard(fn, "ComputationGraph.output")
            self._jit_cache[cache_key] = fn
        rng = (_rng.fold_name(_rng.key(self.training.seed),
                              f"output_{self.iteration_count}")
               if train else None)
        outs = fn(self.params, self._states_map(), inputs, rng)
        return outs[0] if len(outs) == 1 else outs

    def rnn_time_step(self, *inputs):
        """Streaming inference: feed one (or a few) timesteps, carrying each
        recurrent vertex's h/c between calls (parity: the reference
        ComputationGraph's ``rnnTimeStep`` with per-vertex state maps).
        Inputs: [b, f] (single step, output squeezed back) or [b, t, f]."""
        inputs = [jnp.asarray(x) for x in _as_list(
            inputs[0] if len(inputs) == 1 and isinstance(inputs[0], (list, tuple))
            else list(inputs))]
        squeeze = inputs[0].ndim == 2
        if squeeze:
            inputs = [x[:, None, :] for x in inputs]
        if self._rnn_state is None:
            # seed the streaming carries (LSTM h/c zeros; attention K/V
            # caches when max_cache_t is set) — apply() distinguishes a
            # streaming call from plain output() by the presence of the
            # carried cache
            self._rnn_state = self._zero_rnn_carry(inputs[0].shape[0])
            self._rnn_steps_fed = 0
        # strict-mode streaming caches refuse the overflowing chunk
        # host-side, before it can touch the cache
        _precheck_streamed_steps(self, inputs[0].shape[1])
        cache_key = f"rnn_time_step@{_xla.trace_env_key()}"
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            @jax.jit
            def fn(params, states, inputs):
                acts, new_states = self._forward(params, states, inputs,
                                                 train=False)
                carry = {name: {k: v for k, v in st.items()
                                if k in ("h", "c")}
                         for name, st in new_states.items()}
                return [acts[n] for n in self.conf.network_outputs], carry
            fn = _xla.retrace_guard(fn, "ComputationGraph.rnn_time_step")
            self._jit_cache[cache_key] = fn
        outs, self._rnn_state = fn(self.params,
                                   self._states_map(self._rnn_state), inputs)
        # count only steps the cache actually absorbed (a rejected chunk
        # raised above and never touched it)
        _note_streamed_steps(self, inputs[0].shape[1])
        if squeeze:
            outs = [o[:, 0, :] if o.ndim == 3 else o for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def rnn_clear_previous_state(self) -> None:
        """Reset the streaming rnn carry (parity: ``rnnClearPreviousState``)."""
        self._rnn_state = None
        self._rnn_steps_fed = 0

    def feed_forward(self, *inputs, train: bool = False) -> Dict[str, jax.Array]:
        """All vertex activations keyed by name."""
        inputs = [jnp.asarray(x) for x in _as_list(
            inputs[0] if len(inputs) == 1 and isinstance(inputs[0], (list, tuple))
            else list(inputs))]
        acts, _ = self._forward(self.params, self._states_map(), inputs,
                                train=train)
        return acts

    # ------------------------------------------------------------------
    # loss (parity: computeGradientAndScore :912 — score summed over outputs)
    # ------------------------------------------------------------------

    def _loss_fn(self, params, states, inputs, labels, masks, rng, *,
                 collect_stats=False):
        # collect_stats: falsy = plain loss; True or a health.StatsConfig
        # (whose act_sample bounds the activation reductions) additionally
        # returns per-vertex activation summaries through the aux output
        if not self._output_layer_names:
            raise ValueError(
                "no output vertex has a loss (need OutputLayer/RnnOutputLayer/"
                "LossLayer at a network output to train)")
        # stats collection summarizes every vertex activation in the main
        # walk — it bypasses the remat path (same trade as the sequential
        # runtime: visibility over the memory saving)
        if self.training.gradient_checkpointing and not collect_stats:
            if masks is None or all(m is None for m in masks):
                return self._loss_fn_segmented(params, states, inputs,
                                               labels, rng)
            # masked graphs keep the unsegmented walk (mask bookkeeping is
            # per-vertex host-side state across segment boundaries) — say
            # so loudly rather than silently dropping the memory saving
            import warnings
            warnings.warn(
                "gradient_checkpointing is ignored for masked "
                "ComputationGraph inputs — the full-activation path runs",
                stacklevel=2)
        # forward everything EXCEPT the output-layer vertices' own apply;
        # for those we need the hidden input to compute_score_array
        out_set = set(self._output_layer_names)
        acts: Dict[str, jax.Array] = dict(zip(self.conf.network_inputs, inputs))
        mask_map: Dict[str, Optional[jax.Array]] = dict(
            zip(self.conf.network_inputs,
                masks if masks is not None else [None] * len(inputs)))
        new_states: Dict[str, Dict[str, jax.Array]] = {}
        label_map = dict(zip(self.conf.network_outputs, labels))
        # output-layer vertices that also feed downstream vertices must still
        # publish their activation (reference ComputationGraph supports output
        # layers with consumers); XLA CSE merges the duplicated layer forward
        consumed = {i for ins in self.conf.vertex_inputs.values() for i in ins}
        mbs = self._minibatch_map(inputs[0].shape[0])
        act_stats: Dict[str, Dict[str, jax.Array]] = {}
        total = 0.0
        for name in self.topo_order:
            in_names = self.conf.vertex_inputs[name]
            in_masks = [mask_map.get(i) for i in in_names]
            vrng = None if rng is None else _rng.fold_name(rng, name)
            is_out = name in out_set
            if is_out:
                total = total + self._output_score(
                    params, name, acts[in_names[0]], label_map[name],
                    in_masks[0] if in_masks else None, vrng,
                    minibatch=mbs[in_names[0]])
            if not is_out or name in consumed:
                out, st = self._apply_vertex(name, params[name], acts,
                                             states[name], vrng, train=True,
                                             in_masks=in_masks,
                                             minibatch=mbs[in_names[0]])
                acts[name] = out
                mask_map[name] = self.conf.vertices[name].output_mask(
                    in_masks, minibatch=acts[in_names[0]].shape[0])
                new_states[name] = st
                if collect_stats:
                    act_stats[name] = _health.act_summary(
                        out, getattr(collect_stats, "act_sample", 0))
            else:
                new_states[name] = {}
        total = total + self._reg_penalty(params)
        # layers may surface auxiliary objectives through their state
        # (e.g. MoELayer's load-balancing loss, pre-scaled by aux_weight)
        for st in new_states.values():
            if "aux_loss" in st:
                total = total + st["aux_loss"]
        loss_dtype = (jnp.float64 if self.policy.param_dtype == jnp.float64
                      else jnp.float32)
        if collect_stats:
            return total.astype(loss_dtype), (new_states, act_stats)
        return total.astype(loss_dtype), new_states

    def _output_score(self, params, name, hidden, y, mask, vrng=None,
                      minibatch=None):
        """One output vertex's loss contribution from its HIDDEN input —
        preprocessor, fused score array, masked denominator. Shared by the
        plain and gradient-checkpointed loss paths. ``vrng`` is this
        vertex's rng fold — the SAME one ``_apply_vertex`` uses, so a
        sampling preprocessor on a consumed output vertex draws one sample,
        not two different ones."""
        v = self.conf.vertices[name]
        out_mask = mask
        if v.preprocessor is not None:
            mb = minibatch if minibatch is not None else hidden.shape[0]
            hidden = call_preprocessor(v.preprocessor, hidden,
                                       minibatch_size=mb, rng=vrng)
            out_mask = v.preprocessor.transform_mask(out_mask,
                                                     minibatch_size=mb)
        score_arr = v.layer.compute_score_array(
            params[name], hidden, y, mask=out_mask, policy=self.policy)
        denom = _losses.masked_denominator(
            out_mask, y, score_arr.shape[0],
            sparse=_losses.is_sparse(v.layer.loss))
        return jnp.sum(score_arr) / denom

    def _loss_fn_segmented(self, params, states, inputs, labels, rng):
        """Gradient-checkpointed loss: the DAG runs through
        ``_forward_segmented`` (only ~sqrt(V) boundary activations stay
        live for the backward), then the loss heads score the published
        hidden activations exactly like the unsegmented path."""
        acts, new_states = self._forward_segmented(params, states, inputs,
                                                   rng=rng)
        label_map = dict(zip(self.conf.network_outputs, labels))
        mbs = self._minibatch_map(inputs[0].shape[0])
        total = 0.0
        for name in self._output_layer_names:
            hidden = acts[self.conf.vertex_inputs[name][0]]
            vrng = None if rng is None else _rng.fold_name(rng, name)
            total = total + self._output_score(
                params, name, hidden, label_map[name], None, vrng,
                minibatch=mbs[self.conf.vertex_inputs[name][0]])
        total = total + self._reg_penalty(params)
        # layers may surface auxiliary objectives through their state
        # (e.g. MoELayer's load-balancing loss, pre-scaled by aux_weight)
        for st in new_states.values():
            if "aux_loss" in st:
                total = total + st["aux_loss"]
        loss_dtype = (jnp.float64 if self.policy.param_dtype == jnp.float64
                      else jnp.float32)
        return total.astype(loss_dtype), new_states

    def _reg_penalty(self, params):
        if not self.training.regularization:
            return 0.0
        acc_dtype = (jnp.float64 if self.policy.param_dtype == jnp.float64
                     else jnp.float32)
        total = 0.0
        for name in self.topo_order:
            layer = self._vertex_layer(name)
            if layer is None:
                continue
            l1 = float(layer.l1 or 0.0)
            l2 = float(layer.l2 or 0.0)
            if l1 == 0.0 and l2 == 0.0:
                continue
            lp = params[name]
            for pname in layer.regularized_params():
                if pname not in lp:
                    continue
                w = lp[pname].astype(acc_dtype)
                if l1:
                    total = total + l1 * jnp.sum(jnp.abs(w))
                if l2:
                    total = total + 0.5 * l2 * jnp.sum(jnp.square(w))
        return total

    def score_for(self, inputs, labels, masks=None) -> float:
        inputs = [jnp.asarray(x) for x in _as_list(inputs)]
        labels = [jnp.asarray(y) for y in _as_list(labels)]
        if masks is not None:
            masks = [None if m is None else jnp.asarray(m)
                     for m in _as_list(masks)]
        loss, _ = self._loss_fn(self.params, self._states_map(), inputs,
                                labels, masks, None)
        return float(loss)

    def score(self) -> Optional[float]:
        if self._score is None:
            return None
        self._score = float(self._score)
        return self._score

    # ------------------------------------------------------------------
    # the jitted train step + fit
    # ------------------------------------------------------------------

    def _make_train_step(self, stats_cfg: Optional[_health.StatsConfig] = None):
        t = self.training
        norm_kind = t.gradient_normalization
        norm_thr = float(t.gradient_normalization_threshold)
        updater = self._updater
        collect = stats_cfg is not None

        def step(params, opt_state, states, inputs, labels, masks, rng, it):
            loss, new_states, grads_raw, act_stats = \
                _health.value_grad_with_stats(
                    self._loss_fn, stats_cfg, params, states, inputs,
                    labels, masks, rng)
            grads = _updaters.normalize_gradients(grads_raw, norm_kind,
                                                  norm_thr)
            deltas, opt_state = updater.update(grads, opt_state, it)
            params = _updaters.apply_updates(params, deltas)
            if not collect:
                return params, opt_state, new_states, loss
            # per-layer health stats in the SAME dispatch: raw (pre-norm)
            # grads, the applied deltas, and the post-update params
            stats = _health.model_stats(params, grads_raw, deltas,
                                        act_stats, stats_cfg, loss=loss)
            return params, opt_state, new_states, loss, stats

        return jax.jit(step, donate_argnums=(0, 1),
                       compiler_options=_xla.train_step_options())

    def _train_step(self):
        # explicit override first (ParallelWrapper installs its sharded
        # SPMD step here; an override is pinned, not trace-env-keyed and
        # not stats-keyed — sharded steps do not collect health stats)
        fn = self._jit_cache.get("train_step_override")
        if fn is not None:
            return fn
        cfg = self.health_stats
        suffix = "" if cfg is None else f"|stats={cfg.trace_key()}"
        cache_key = f"train_step@{_xla.trace_env_key()}{suffix}"
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            # distinct guard name for the stats variant: the no-stats
            # trace's retrace pin must not move when stats toggle
            name = ("ComputationGraph.train_step" if cfg is None
                    else "ComputationGraph.train_step_stats")
            fn = _xla.retrace_guard(self._make_train_step(cfg), name)
            self._jit_cache[cache_key] = fn
        return fn

    def enable_health_stats(self, config=True) -> None:
        """Compute per-layer training-health stats (util.health) INSIDE
        the train dispatch from the next fit call on: the stats-keyed jit
        cache traces a separate program, so the cached no-stats trace is
        untouched and toggling back off reuses it without a recompile.
        Consumers read :func:`util.health.latest_stats` — one host sync
        per read, the snapshot carries the step loss."""
        self.health_stats = _health.StatsConfig.coerce(config)

    def disable_health_stats(self) -> None:
        self.health_stats = None

    def set_listeners(self, *listeners) -> None:
        # Accept both varargs and a single collection (ref Model.setListeners
        # has both overloads).
        if len(listeners) == 1 and isinstance(listeners[0], (list, tuple)):
            listeners = tuple(listeners[0])
        self.listeners = list(listeners)

    def add_listener(self, listener) -> None:
        self.listeners.append(listener)

    def _fire_iteration(self, batch_size, loss):
        self.iteration_count += 1
        if not self.listeners:
            return
        # LazyScore delivery: the device loss syncs to host only when a
        # listener actually reads it (host scalars from the fused-scan
        # replay pass through)
        from ..util.ingest import as_listener_score
        score = as_listener_score(loss)
        for l in self.listeners:
            if hasattr(l, "record_batch"):
                l.record_batch(batch_size)
            l.iteration_done(self, self.iteration_count, score)

    def _make_train_scan(self, stats_cfg: Optional[_health.StatsConfig] = None):
        """K train steps fused into ONE lax.scan XLA program (same design as
        MultiLayerNetwork._make_train_scan). With ``stats_cfg`` the scan
        also emits the health-stats pytree of the LAST step."""
        t = self.training
        norm_kind = t.gradient_normalization
        norm_thr = float(t.gradient_normalization_threshold)
        updater = self._updater
        base = _rng.key(t.seed)
        collect = stats_cfg is not None

        def one(carry, batch):
            params, opt_state, states, it = carry
            xs, ys, masks = batch
            # per-step rng derived from the TRACED counter — computing keys
            # eagerly from the host-side update count bakes fresh constants
            # into the program and forces a recompile every call
            rng = jax.random.fold_in(base, it)
            loss, new_states, grads_raw, act_stats = \
                _health.value_grad_with_stats(
                    self._loss_fn, stats_cfg, params, states, xs, ys,
                    masks, rng)
            grads = _updaters.normalize_gradients(grads_raw, norm_kind,
                                                  norm_thr)
            deltas, opt_state = updater.update(grads, opt_state, it)
            params = _updaters.apply_updates(params, deltas)
            kept = {name: {k: new_states[name].get(k, v)
                           for k, v in st_old.items()}
                    for name, st_old in states.items()}
            if collect:
                stats = _health.model_stats(params, grads_raw, deltas,
                                            act_stats, stats_cfg, loss=loss)
                return (params, opt_state, kept, it + 1), (loss, stats)
            return (params, opt_state, kept, it + 1), loss

        def scan_steps(params, opt_state, states, xs, ys, masks, it0):
            (params, opt_state, states, _), ys_out = jax.lax.scan(
                one, (params, opt_state, states, it0), (xs, ys, masks),
                unroll=_xla.scan_unroll())
            if collect:
                losses, stats_seq = ys_out
                last_stats = jax.tree_util.tree_map(lambda a: a[-1],
                                                    stats_seq)
                return params, opt_state, states, losses, last_stats
            return params, opt_state, states, ys_out

        return jax.jit(scan_steps, donate_argnums=(0, 1),
                       compiler_options=_xla.train_step_options())

    def fit_scan(self, xs, ys, masks=None):
        """Train on K pre-staged batches in one dispatch. xs/ys: [k, b, ...]
        arrays or lists of such (multi-input/multi-output); returns [k] losses."""
        xs = [jnp.asarray(a) for a in _as_list(xs)]
        ys = [jnp.asarray(a) for a in _as_list(ys)]
        self._reject_tbptt([x[0] for x in xs], "fit_scan")
        k = xs[0].shape[0]
        if masks is not None:
            masks = [None if m is None else jnp.asarray(m)
                     for m in _as_list(masks)]
        cfg = self.health_stats
        suffix = "" if cfg is None else f"|stats={cfg.trace_key()}"
        cache_key = f"train_scan@{_xla.trace_env_key()}{suffix}"
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            name = ("ComputationGraph.train_scan" if cfg is None
                    else "ComputationGraph.train_scan_stats")
            fn = _xla.retrace_guard(self._make_train_scan(cfg), name)
            self._jit_cache[cache_key] = fn
        it0 = jnp.asarray(self._update_count, jnp.int32)
        out = fn(
            self.params, self.updater_state, self._states_map(), xs, ys,
            masks, it0)
        if cfg is not None:
            params, opt_state, new_states, losses, stats = out
            self._last_health_stats = _health.DeviceStats(
                stats, iteration=self.iteration_count + k,
                model="ComputationGraph")
        else:
            params, opt_state, new_states, losses = out
        self.params = params
        self.updater_state = opt_state
        self._update_count += k
        self._persist_states(new_states)
        self._score = losses[-1]
        # replay per-step losses so listener/stats semantics (score history,
        # throughput via record_batch) match fit()/fit_batch for k updates
        if self.listeners:
            batch_size = int(xs[0].shape[1])
            per_step = np.asarray(losses)
            for i in range(k):
                self._fire_iteration(batch_size, per_step[i])
        else:
            self.iteration_count += k
        return losses

    def _make_train_repeat(self, stats_cfg: Optional[_health.StatsConfig] = None):
        """K train steps on ONE closed-over batch via lax.scan over step
        indices — constant HBM regardless of K. Used by fit_repeated().
        With ``stats_cfg`` the scan also emits the health-stats pytree of
        the LAST step (same window semantics as fit_scan)."""
        t = self.training
        norm_kind = t.gradient_normalization
        norm_thr = float(t.gradient_normalization_threshold)
        updater = self._updater
        base = _rng.key(t.seed)
        collect = stats_cfg is not None

        def one(xs, ys, masks, carry, it):
            params, opt_state, states = carry
            rng = jax.random.fold_in(base, it)
            loss, new_states, grads_raw, act_stats = \
                _health.value_grad_with_stats(
                    self._loss_fn, stats_cfg, params, states, xs, ys,
                    masks, rng)
            grads = _updaters.normalize_gradients(grads_raw, norm_kind,
                                                  norm_thr)
            deltas, opt_state = updater.update(grads, opt_state, it)
            params = _updaters.apply_updates(params, deltas)
            kept = {name: {k: new_states[name].get(k, v)
                           for k, v in st_old.items()}
                    for name, st_old in states.items()}
            if collect:
                stats = _health.model_stats(params, grads_raw, deltas,
                                            act_stats, stats_cfg, loss=loss)
                return (params, opt_state, kept), (loss, stats)
            return (params, opt_state, kept), loss

        def repeat_steps(params, opt_state, states, xs, ys, masks, it0, k):
            # unroll (default 2): XLA removes inter-iteration carry copies
            # between the paired bodies (measured ~1.2 ms/step on ResNet-50
            # @ v5e); DL4JTPU_SCAN_UNROLL overrides for tuning
            (params, opt_state, states), ys_out = jax.lax.scan(
                functools.partial(one, xs, ys, masks),
                (params, opt_state, states), it0 + jnp.arange(k),
                unroll=_xla.scan_unroll())
            if collect:
                losses, stats_seq = ys_out
                last_stats = jax.tree_util.tree_map(lambda a: a[-1],
                                                    stats_seq)
                return params, opt_state, states, losses, last_stats
            return params, opt_state, states, ys_out

        return jax.jit(repeat_steps, donate_argnums=(0, 1, 2),
                       static_argnums=(7,),
                       compiler_options=_xla.train_step_options())

    def fit_repeated(self, inputs, labels, k: int, masks=None):
        """Run K optimizer updates on one pre-staged batch in a single device
        dispatch (lax.scan over step indices). The on-chip analog of calling
        ``fit_batch`` K times: same per-update rng folding, iteration counters,
        and listener firing — but one dispatch and one batch of HBM. Used for
        steady-state throughput measurement; returns [k] losses."""
        inputs = [jnp.asarray(x) for x in _as_list(inputs)]
        labels = [jnp.asarray(y) for y in _as_list(labels)]
        self._reject_tbptt(inputs, "fit_repeated")
        if masks is not None:
            masks = [None if m is None else jnp.asarray(m)
                     for m in _as_list(masks)]
        cfg = self.health_stats
        suffix = "" if cfg is None else f"|stats={cfg.trace_key()}"
        cache_key = f"train_repeat@{_xla.trace_env_key()}{suffix}"
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            name = ("ComputationGraph.train_repeat" if cfg is None
                    else "ComputationGraph.train_repeat_stats")
            fn = _xla.retrace_guard(self._make_train_repeat(cfg), name)
            self._jit_cache[cache_key] = fn
        it0 = jnp.asarray(self._update_count, jnp.int32)
        out = fn(
            self.params, self.updater_state, self._states_map(), inputs,
            labels, masks, it0, int(k))
        if cfg is not None:
            params, opt_state, new_states, losses, stats = out
            self._last_health_stats = _health.DeviceStats(
                stats, iteration=self.iteration_count + int(k),
                model="ComputationGraph")
        else:
            params, opt_state, new_states, losses = out
        self.params = params
        self.updater_state = opt_state
        self._update_count += int(k)
        self._persist_states(new_states)
        self._score = losses[-1]
        if self.listeners:
            batch_size = int(inputs[0].shape[0])
            per_step = np.asarray(losses)
            for i in range(int(k)):
                self._fire_iteration(batch_size, per_step[i])
        else:
            self.iteration_count += int(k)
        return losses

    def fit_batch(self, inputs, labels, masks=None):
        """One update (tbptt-aware). inputs/labels: array or list of arrays
        (multi-input / multi-output); masks: optional list of feature
        masks."""
        inputs = [jnp.asarray(x) for x in _as_list(inputs)]
        labels = [jnp.asarray(y) for y in _as_list(labels)]
        if masks is not None:
            masks = [None if m is None else jnp.asarray(m)
                     for m in _as_list(masks)]
        T = self._tbptt_T(inputs)
        if T is not None and T > self.conf.tbptt_fwd_length:
            return self._fit_tbptt(inputs, labels, masks, T)
        loss = self._step_and_update(inputs, labels, masks, None)
        self._score = loss
        self._fire_iteration(inputs[0].shape[0], loss)
        return loss

    def _reject_tbptt(self, inputs, api: str) -> None:
        """The fused-scan paths run ONE full-sequence BPTT update per batch;
        silently doing that under a truncated_bptt config would change both
        memory behavior and optimization semantics — refuse loudly."""
        T = self._tbptt_T(inputs)
        if T is not None and T > self.conf.tbptt_fwd_length:
            raise ValueError(
                f"{api} does not chunk truncated BPTT (T={T} > "
                f"tbptt_fwd_length={self.conf.tbptt_fwd_length}); use "
                "fit()/fit_batch(), or pre-chunk the sequences")

    def _tbptt_T(self, inputs):
        """The time-series length for truncated BPTT, scanning ALL inputs
        (the first may be a static [b, f] feature — reference CG scans the
        whole input set). None when tbptt is off or nothing is temporal;
        mixed 3-D lengths are ambiguous and raise."""
        if self.conf.backprop_type != "truncated_bptt":
            return None
        ts = {int(x.shape[1]) for x in inputs if x.ndim == 3}
        if not ts:
            return None
        if len(ts) > 1:
            raise ValueError(
                f"truncated_bptt with differing sequence lengths {sorted(ts)} "
                "across inputs is ambiguous — align or pad them")
        return ts.pop()

    def _fit_tbptt(self, inputs, labels, masks, T):
        """Truncated BPTT over the DAG: slice [b, t, ...] into fwd-length
        chunks, carrying every recurrent vertex's h/c across chunks with
        gradients stopped at the boundary (parity: the reference
        ComputationGraph's doTruncatedBPTT)."""
        length = self.conf.tbptt_fwd_length
        batch = inputs[0].shape[0]

        def _slice(a, start, end):
            return (a[:, start:end]
                    if a is not None and a.ndim == 3 and a.shape[1] == T
                    else a)

        rnn_state = self._zero_rnn_carry(batch)
        loss = 0.0
        for start in range(0, T, length):
            end = min(start + length, T)
            xs = [_slice(x, start, end) for x in inputs]
            ys = [_slice(y, start, end) for y in labels]
            ms = (None if masks is None else
                  [m[:, start:end] if (m is not None and m.ndim >= 2
                                       and m.shape[1] == T) else m
                   for m in masks])
            loss = self._step_and_update(xs, ys, ms, rnn_state)
            rnn_state = self._last_rnn_carry
            # one iteration (and listener firing) per TBPTT segment, matching
            # the reference's doTruncatedBPTT accounting: listeners see every
            # iteration number, not one per full-sequence batch.
            self._score = loss
            self._fire_iteration(batch, loss)
        return loss

    def _zero_rnn_carry(self, batch):
        mbs = self._minibatch_map(batch)
        carry = {}
        for name in self.topo_order:
            layer = self._vertex_layer(name)
            # max_cache_t None = a streaming-capable layer (attention)
            # whose cache is disabled — it carries nothing
            if (layer is not None and hasattr(layer, "_zero_state")
                    and getattr(layer, "max_cache_t", True) is not None):
                mb = mbs[self.conf.vertex_inputs[name][0]]
                h, c = layer._zero_state(mb, self.policy)
                carry[name] = {"h": h, "c": c}
            else:
                carry[name] = {}
        return carry

    def _step_and_update(self, inputs, labels, masks, rnn_state):
        rng = _rng.fold_name(_rng.key(self.training.seed),
                             f"update_{self._update_count}")
        it = jnp.asarray(self._update_count, jnp.int32)
        out = self._train_step()(
            self.params, self.updater_state, self._states_map(rnn_state),
            inputs, labels, masks, rng, it)
        # sharded overrides always return 4 outputs; only the stats
        # variant of the owned step returns the fifth (the stats pytree)
        if len(out) == 5:
            params, opt_state, new_states, loss, stats = out
            self._last_health_stats = _health.DeviceStats(
                stats, iteration=self.iteration_count + 1,
                model="ComputationGraph")
        else:
            params, opt_state, new_states, loss = out
        self.params = params
        self.updater_state = opt_state
        self._update_count += 1
        # stop-gradient boundary for tbptt: carry values, not graph
        self._last_rnn_carry = jax.tree_util.tree_map(
            jax.lax.stop_gradient,
            {name: {k: v for k, v in st.items() if k in ("h", "c")}
             for name, st in new_states.items()})
        self._persist_states(new_states)
        return loss

    def fit(self, data, labels=None, *, epochs: int = 1,
            coalesce: Optional[int] = None, session=None) -> None:
        """Train from (inputs, labels), a DataSet/MultiDataSet, or an iterator
        of either (parity: fit variants :614-760).

        Same async-dispatch loop as ``MultiLayerNetwork.fit``: background
        device staging for iterator sources, bounded in-flight window,
        LazyScore listener delivery, lazy epoch-start resets (the final
        epoch never restarts the producer), optional same-shape
        coalescing via ``coalesce=K`` / ``DL4JTPU_COALESCE_K``.
        """
        from ..util.ingest import run_fit_loop
        if self.params is None:
            self.init()
        run_fit_loop(self, data, labels, None, epochs, coalesce,
                     model_label="ComputationGraph", session=session)

    @staticmethod
    def _as_batches(data, labels=None, mask=None):
        from ..util.batching import iter_batches
        return iter_batches(data, labels, mask)

    # ------------------------------------------------------------------
    # layerwise pretraining (parity: ComputationGraph.pretrain :509-523)
    # ------------------------------------------------------------------

    def pretrain(self, data, labels=None, *, epochs: int = 1,
                 learning_rate: Optional[float] = None) -> None:
        """Greedy layerwise pretraining of AutoEncoder/RBM layer vertices,
        in topological order: each pretrainable vertex trains on its frozen
        upstream activations, then the walk moves deeper."""
        if self.params is None:
            self.init()
        lr = float(learning_rate if learning_rate is not None
                   else self.training.learning_rate)
        pre = [n for n in self.topo_order
               if self._vertex_layer(n) is not None
               and (hasattr(self._vertex_layer(n), "pretrain_loss")
                    or hasattr(self._vertex_layer(n),
                               "contrastive_divergence_grads"))]
        if not pre:
            return
        from .conf.pretrain import make_pretrain_step
        batches = list(self._as_batches(data, labels, None))
        for name in pre:
            step = make_pretrain_step(self._vertex_layer(name), lr,
                                      self.policy)
            # upstream is frozen while this vertex trains, so its input
            # activations are constant across epochs — but holding them all
            # is O(dataset) device memory; only precompute when the reuse
            # (epochs>1) and the footprint (few batches) justify it
            cache_all = epochs > 1 and len(batches) <= 64

            def _hid(ins):
                return self._vertex_input_activation(
                    name, [jnp.asarray(np.asarray(x)) for x in _as_list(ins)])

            hiddens = ([_hid(ins) for ins, _, _ in batches]
                       if cache_all else None)
            for e in range(epochs):
                for bi, (ins, _, _) in enumerate(batches):
                    hidden = hiddens[bi] if cache_all else _hid(ins)
                    rng = _rng.fold_name(_rng.key(self.training.seed),
                                         f"pre_{name}_{e}_{bi}")
                    self.params[name] = step(self.params[name], hidden, rng)

    def _vertex_input_activation(self, name: str, inputs: List[jax.Array]):
        """The (preprocessed) input activation a layer vertex sees, with all
        upstream vertices frozen in eval mode."""
        # trace_env_key: frozen-vertex forwards trace the same attention
        # routing flags as output()/fit — a flag flip must retrace here too
        cache_key = f"pre_acts_{name}@{_xla.trace_env_key()}"
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            @jax.jit
            def fn(params, states, inputs):
                acts, _ = self._forward(params, states, inputs, train=False)
                x = acts[self.conf.vertex_inputs[name][0]]
                v = self.conf.vertices[name]
                if v.preprocessor is not None:
                    mbs = self._minibatch_map(inputs[0].shape[0])
                    x = v.preprocessor(
                        x,
                        minibatch_size=mbs[self.conf.vertex_inputs[name][0]])
                return x
            self._jit_cache[cache_key] = fn
        return fn(self.params, self._states_map(), inputs)


    # ------------------------------------------------------------------
    # evaluation bridge
    # ------------------------------------------------------------------

    def evaluate(self, data, labels=None):
        """Classification evaluation; DataSet iterators carrying
        ``example_metadata`` flow provenance into the returned Evaluation
        (``get_prediction_errors()`` — parity: ``Evaluation.java:195``)."""
        from ..eval import Evaluation
        from ..util.batching import iter_batches
        ev = Evaluation()
        # fit() no longer resets the source after its final epoch; revive
        # an exhausted resettable iterator instead of evaluating nothing
        if (hasattr(data, "has_next") and not data.has_next()
                and hasattr(data, "reset")):
            data.reset()
        for x, y, m, meta in iter_batches(data, labels, with_meta=True):
            out = self.output(jnp.asarray(np.asarray(x)))
            ev.eval(np.asarray(y), np.asarray(out),
                    mask=None if m is None else np.asarray(m),
                    metadata=meta)
        if hasattr(data, "reset"):
            data.reset()
        return ev

    def clone_params(self):
        return jax.tree_util.tree_map(lambda p: jnp.array(p), self.params)
