"""Weight initialization schemes.

Semantic parity with the reference's WeightInit enum + WeightInitUtil
(reference ``nn/weights/WeightInit.java:48-54``,
``nn/weights/WeightInitUtil.java:66-112``):

  DISTRIBUTION    sample from a configured distribution
  ZERO            zeros
  SIGMOID_UNIFORM U(-r, r), r = 4*sqrt(6/(fanIn+fanOut))
  UNIFORM         U(-a, a), a = 1/sqrt(fanIn)
  XAVIER          N(0, 2/(fanIn+fanOut))
  XAVIER_UNIFORM  U(-s, s), s = sqrt(6/(fanIn+fanOut))
  XAVIER_FAN_IN   N(0, 1/fanIn)
  XAVIER_LEGACY   N(0, 1/(shape[0]+shape[1]))
  RELU            N(0, 2/fanIn)  (He init)
  RELU_UNIFORM    U(-u, u), u = sqrt(6/fanIn)
  NORMALIZED      (U(0,1) - 0.5) / shape[0]

Implemented as pure functions of a PRNG key — no global RNG state.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

VALID = (
    "DISTRIBUTION", "ZERO", "ONES", "SIGMOID_UNIFORM", "UNIFORM", "XAVIER",
    "XAVIER_UNIFORM", "XAVIER_FAN_IN", "XAVIER_LEGACY", "RELU", "RELU_UNIFORM",
    "NORMALIZED", "IDENTITY", "LECUN_NORMAL", "LECUN_UNIFORM", "VAR_SCALING_NORMAL_FAN_AVG",
)


@dataclasses.dataclass(frozen=True)
class Distribution:
    """Serializable distribution spec for WeightInit.DISTRIBUTION.

    Mirrors the reference's NormalDistribution/UniformDistribution/
    BinomialDistribution config classes (``nn/conf/distribution/``).
    """

    kind: str = "normal"  # normal | uniform | constant
    mean: float = 0.0
    std: float = 1.0
    lower: float = -1.0
    upper: float = 1.0
    value: float = 0.0

    def sample(self, key, shape, dtype):
        if self.kind == "normal":
            return self.mean + self.std * jax.random.normal(key, shape, dtype)
        if self.kind == "uniform":
            return jax.random.uniform(key, shape, dtype, self.lower, self.upper)
        if self.kind == "constant":
            return jnp.full(shape, self.value, dtype)
        raise ValueError(f"unknown distribution kind {self.kind!r}")

    def to_dict(self):
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d):
        return Distribution(**d)


def init_weights(
    key: jax.Array,
    shape: Sequence[int],
    scheme: str,
    fan_in: float,
    fan_out: float,
    distribution: Optional[Distribution] = None,
    dtype=jnp.float32,
) -> jax.Array:
    scheme = scheme.upper()
    shape = tuple(shape)
    if scheme == "ZERO":
        return jnp.zeros(shape, dtype)
    if scheme == "ONES":
        return jnp.ones(shape, dtype)
    if scheme == "IDENTITY":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires a square 2d shape")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme == "DISTRIBUTION":
        if distribution is None:
            raise ValueError("WeightInit DISTRIBUTION requires a distribution")
        return distribution.sample(key, shape, dtype)
    if scheme == "NORMALIZED":
        return (jax.random.uniform(key, shape, dtype) - 0.5) / shape[0]
    if scheme == "XAVIER":
        return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / (fan_in + fan_out))
    if scheme == "XAVIER_UNIFORM":
        s = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -s, s)
    if scheme == "XAVIER_FAN_IN":
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if scheme == "XAVIER_LEGACY":
        return jax.random.normal(key, shape, dtype) / math.sqrt(shape[0] + shape[1])
    if scheme == "SIGMOID_UNIFORM":
        r = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -r, r)
    if scheme == "UNIFORM":
        a = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "RELU":
        return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / fan_in)
    if scheme == "RELU_UNIFORM":
        u = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -u, u)
    if scheme == "LECUN_NORMAL":
        return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / fan_in)
    if scheme == "LECUN_UNIFORM":
        b = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -b, b)
    if scheme == "VAR_SCALING_NORMAL_FAN_AVG":
        return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / (fan_in + fan_out))
    raise ValueError(f"unknown WeightInit scheme {scheme!r}; valid: {VALID}")
