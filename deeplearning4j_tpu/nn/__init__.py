"""Neural network package: config DSL, functional layers, runtime networks."""
