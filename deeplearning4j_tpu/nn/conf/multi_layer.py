"""MultiLayerConfiguration: ordered layer stack + training flags + serde.

Parity: reference ``nn/conf/MultiLayerConfiguration.java`` (tbptt defaults=20
``:67-68``, JSON/YAML round-trip ``:75-117``, setInputType-driven inference
``:256``/``:370-409``). JSON is the persistence/versioning story — it is what
goes inside checkpoints (ModelSerializer parity in util/serialization.py).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from .inputs import InputType
from .layers import Layer, layer_from_dict, layer_to_dict
from .preprocessors import InputPreProcessor, preprocessor_from_dict
from .training import TrainingConfig

# ensure recurrent/pretrain layer types are registered for serde
from . import recurrent as _recurrent  # noqa: F401
from . import pretrain as _pretrain  # noqa: F401


@dataclasses.dataclass
class MultiLayerConfiguration:
    layers: List[Layer]
    input_preprocessors: Dict[int, InputPreProcessor] = dataclasses.field(default_factory=dict)
    training: TrainingConfig = dataclasses.field(default_factory=TrainingConfig)
    input_type: Optional[InputType] = None
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20

    # ---- serde (parity: toJson/fromJson/toYaml/fromYaml :75-117) ----
    def to_dict(self) -> dict:
        return {
            "format_version": 1,
            "framework": "deeplearning4j_tpu",
            "layers": [layer_to_dict(l) for l in self.layers],
            "input_preprocessors": {str(i): p.to_dict()
                                    for i, p in self.input_preprocessors.items()},
            "training": self.training.to_dict(),
            "input_type": self.input_type.to_dict() if self.input_type else None,
            "backprop": self.backprop,
            "pretrain": self.pretrain,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
        }

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration(
            layers=[layer_from_dict(l) for l in d["layers"]],
            input_preprocessors={int(i): preprocessor_from_dict(p)
                                 for i, p in d.get("input_preprocessors", {}).items()},
            training=TrainingConfig.from_dict(d.get("training", {})),
            input_type=(InputType.from_dict(d["input_type"])
                        if d.get("input_type") else None),
            backprop=d.get("backprop", True),
            pretrain=d.get("pretrain", False),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
        )

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        import yaml
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        import yaml
        return MultiLayerConfiguration.from_dict(yaml.safe_load(s))

    # ---- convenience ----
    def n_layers(self) -> int:
        return len(self.layers)

    def layer_input_types(self) -> List[InputType]:
        """Per-layer post-preprocessor input types (requires input_type)."""
        if self.input_type is None:
            raise ValueError("input_type not set on this configuration")
        out = []
        cur = self.input_type
        for i, layer in enumerate(self.layers):
            proc = self.input_preprocessors.get(i)
            if proc is not None:
                cur = proc.output_type(cur)
            out.append(cur)
            cur = layer.output_type(cur)
        return out
