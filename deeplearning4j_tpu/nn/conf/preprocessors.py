"""Input preprocessors: shape adapters auto-inserted between layer families.

Parity: reference ``nn/conf/preprocessor/`` (CnnToFeedForwardPreProcessor,
FeedForwardToCnnPreProcessor, RnnToFeedForwardPreProcessor,
FeedForwardToRnnPreProcessor, CnnToRnnPreProcessor, RnnToCnnPreProcessor).

Functional design: each preprocessor is a pure reshape/transpose on the
forward activations; the backward pass is derived by autodiff, so the
reference's hand-written ``backprop()`` methods have no analog here.
Mask transformation (``feedForwardMaskArray`` in the reference) is the
``transform_mask`` hook.

Layout note: CNN activations here are NHWC (TPU-native), so
CnnToFeedForward flattens in (h, w, c) order — this is recorded in the
serialized config so Keras/NCHW importers can insert permutations.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp

from .inputs import InputType

_REGISTRY: Dict[str, Type["InputPreProcessor"]] = {}


def register_preprocessor(name: str):
    def deco(cls):
        cls._type_name = name
        _REGISTRY[name] = cls
        return cls
    return deco


def call_preprocessor(proc: "InputPreProcessor", x, minibatch_size=None,
                      rng=None):
    """Invoke a preprocessor from a network runtime — the ONE place that
    threads the per-layer rng into preprocessors declaring ``wants_rng``
    (stochastic samplers get a fresh fold of the step key; everything else
    keeps the plain pure-reshape call)."""
    if getattr(proc, "wants_rng", False) and rng is not None:
        from ...rng import fold_name
        return proc(x, minibatch_size=minibatch_size,
                    key=fold_name(rng, "preproc"))
    return proc(x, minibatch_size=minibatch_size)


def preprocessor_from_dict(d) -> "InputPreProcessor":
    d = dict(d)
    typ = d.pop("type")
    if "children" in d:  # ComposableInputPreProcessor: nested serde
        d["children"] = tuple(preprocessor_from_dict(c) for c in d["children"])
    return _REGISTRY[typ](**d)


@dataclasses.dataclass(frozen=True)
class InputPreProcessor:
    _type_name = "base"

    def __call__(self, x, minibatch_size=None):
        raise NotImplementedError

    def transform_mask(self, mask, minibatch_size=None):
        return mask

    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def to_dict(self):
        return {"type": self._type_name, **dataclasses.asdict(self)}


@register_preprocessor("cnn_to_feedforward")
@dataclasses.dataclass(frozen=True)
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, minibatch_size=None):  # [b,h,w,c] -> [b, h*w*c]
        return x.reshape(x.shape[0], -1)

    def output_type(self, input_type):
        return InputType.feed_forward(input_type.height * input_type.width
                                      * input_type.channels)


@register_preprocessor("feedforward_to_cnn")
@dataclasses.dataclass(frozen=True)
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, minibatch_size=None):  # [b, h*w*c] -> [b,h,w,c]
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, input_type):
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preprocessor("rnn_to_feedforward")
@dataclasses.dataclass(frozen=True)
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[b, t, f] -> [b*t, f] (time-distributed dense).

    Parity: reference RnnToFeedForwardPreProcessor (which permutes an
    f-ordered NDArray; here a plain reshape has the same row semantics).
    """

    def __call__(self, x, minibatch_size=None):
        return x.reshape(-1, x.shape[-1])

    def transform_mask(self, mask, minibatch_size=None):
        return None if mask is None else mask.reshape(-1)

    def output_type(self, input_type):
        return InputType.feed_forward(input_type.size)


@register_preprocessor("feedforward_to_rnn")
@dataclasses.dataclass(frozen=True)
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    minibatch: int = 0  # optional serde-carried fallback; runtime passes minibatch_size

    def __call__(self, x, minibatch_size=None):
        b = minibatch_size if minibatch_size else self.minibatch
        if not b:
            raise ValueError(
                "FeedForwardToRnnPreProcessor needs minibatch_size to "
                "reconstruct the time axis from [b*t, f]; the network runtime "
                "supplies it — pass minibatch_size= when calling directly")
        return x.reshape(b, -1, x.shape[-1])

    def transform_mask(self, mask, minibatch_size=None):
        if mask is None:
            return None
        b = minibatch_size if minibatch_size else self.minibatch
        return mask.reshape(b, -1)

    def output_type(self, input_type):
        return InputType.recurrent(input_type.size)


@register_preprocessor("cnn_to_rnn")
@dataclasses.dataclass(frozen=True)
class CnnToRnnPreProcessor(InputPreProcessor):
    """[b*t, h, w, c] -> [b, t, h*w*c]."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, minibatch_size=None):
        b = minibatch_size or x.shape[0]
        return x.reshape(b, -1, self.height * self.width * self.channels)

    def output_type(self, input_type):
        return InputType.recurrent(input_type.height * input_type.width
                                   * input_type.channels)


@register_preprocessor("rnn_to_cnn")
@dataclasses.dataclass(frozen=True)
class RnnToCnnPreProcessor(InputPreProcessor):
    """[b, t, h*w*c] -> [b*t, h, w, c]."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, minibatch_size=None):
        return x.reshape(-1, self.height, self.width, self.channels)

    def transform_mask(self, mask, minibatch_size=None):
        return None if mask is None else mask.reshape(-1)

    def output_type(self, input_type):
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preprocessor("reshape")
@dataclasses.dataclass(frozen=True)
class ReshapePreProcessor(InputPreProcessor):
    """Arbitrary reshape (parity: ``ReshapePreProcessor.java:67`` — with
    ``dynamic=True`` the leading dim follows the incoming minibatch).

    The reference also stores ``fromShape`` for its hand-written backprop;
    autodiff derives the inverse reshape here, so only ``to_shape`` is kept.
    """
    to_shape: tuple = ()
    dynamic: bool = True

    def __call__(self, x, minibatch_size=None):
        shape = tuple(int(s) for s in self.to_shape)
        if self.dynamic:
            shape = (x.shape[0],) + shape[1:]
        return x.reshape(shape)

    def output_type(self, input_type):
        tail = tuple(int(s) for s in self.to_shape[1:])
        if len(tail) == 1:
            return InputType.feed_forward(tail[0])
        if len(tail) == 2:
            return InputType.recurrent(tail[1])
        if len(tail) == 3:
            return InputType.convolutional(*tail)
        raise ValueError(f"cannot infer InputType for to_shape={self.to_shape}")

    def to_dict(self):
        return {"type": self._type_name,
                "to_shape": list(self.to_shape), "dynamic": self.dynamic}


@register_preprocessor("zero_mean")
@dataclasses.dataclass(frozen=True)
class ZeroMeanPreProcessor(InputPreProcessor):
    """Subtract per-column batch mean (parity: ``ZeroMeanPrePreProcessor``).

    The reference's ``backprop`` passes cotangents through unchanged, i.e.
    it treats the batch statistic as a constant; ``stop_gradient`` on the
    mean reproduces exactly that.
    """

    def __call__(self, x, minibatch_size=None):
        return x - jax.lax.stop_gradient(x.mean(axis=0, keepdims=True))

    def output_type(self, input_type):
        return input_type


@register_preprocessor("unit_variance")
@dataclasses.dataclass(frozen=True)
class UnitVarianceProcessor(InputPreProcessor):
    """Divide by per-column batch std (parity: ``UnitVarianceProcessor.java:39``,
    incl. the reference's ddof=1 ``std`` and epsilon guard)."""
    eps: float = 1e-5

    def __call__(self, x, minibatch_size=None):
        # ddof=1 is 0/0=NaN for a minibatch of 1; fall back to ddof=0 there
        # (shape is static at trace time, so this is a compile-time branch).
        std = jnp.std(x, axis=0, keepdims=True,
                      ddof=1 if x.shape[0] > 1 else 0) + self.eps
        return x / jax.lax.stop_gradient(std)

    def output_type(self, input_type):
        return input_type


@register_preprocessor("zero_mean_unit_variance")
@dataclasses.dataclass(frozen=True)
class ZeroMeanAndUnitVariancePreProcessor(InputPreProcessor):
    """Column-standardize activations (parity:
    ``ZeroMeanAndUnitVariancePreProcessor.java:38``)."""
    eps: float = 1e-5

    def __call__(self, x, minibatch_size=None):
        mean = x.mean(axis=0, keepdims=True)
        std = jnp.std(x, axis=0, keepdims=True,
                      ddof=1 if x.shape[0] > 1 else 0) + self.eps
        return (x - jax.lax.stop_gradient(mean)) / jax.lax.stop_gradient(std)

    def output_type(self, input_type):
        return input_type


@register_preprocessor("binomial_sampling")
@dataclasses.dataclass(frozen=True)
class BinomialSamplingPreProcessor(InputPreProcessor):
    """Bernoulli-sample activations as probabilities (parity:
    ``BinomialSamplingPreProcessor.java:36`` — the RBM-era stochastic
    binarization).

    Functional RNG: the network runtimes see ``wants_rng`` and pass the
    per-layer fold of the step rng as ``key=`` — fresh samples every
    training step, like the reference's global-RNG draw. Only a direct
    call with no ``key=`` falls back to the deterministic seed-derived key.
    Backward is straight-through (sampling has no gradient), matching the
    reference's identity ``backprop``.
    """
    seed: int = 0
    wants_rng = True

    def __call__(self, x, minibatch_size=None, key=None):
        if key is None:
            key = jax.random.PRNGKey(self.seed)
        sample = jax.random.bernoulli(key, jnp.clip(x, 0.0, 1.0)).astype(x.dtype)
        return x + jax.lax.stop_gradient(sample - x)  # straight-through

    def output_type(self, input_type):
        return input_type


@register_preprocessor("composable")
@dataclasses.dataclass(frozen=True)
class ComposableInputPreProcessor(InputPreProcessor):
    """Chain preprocessors in order (parity:
    ``ComposableInputPreProcessor.java:43``; the reference's reversed
    backprop order falls out of autodiff)."""
    children: tuple = ()

    @property
    def wants_rng(self):
        return any(getattr(p, "wants_rng", False) for p in self.children)

    def __call__(self, x, minibatch_size=None, key=None):
        for i, p in enumerate(self.children):
            if getattr(p, "wants_rng", False) and key is not None:
                x = p(x, minibatch_size=minibatch_size,
                      key=jax.random.fold_in(key, i))
            else:
                x = p(x, minibatch_size=minibatch_size)
        return x

    def transform_mask(self, mask, minibatch_size=None):
        for p in self.children:
            mask = p.transform_mask(mask, minibatch_size=minibatch_size)
        return mask

    def output_type(self, input_type):
        for p in self.children:
            input_type = p.output_type(input_type)
        return input_type

    def to_dict(self):
        return {"type": self._type_name,
                "children": [p.to_dict() for p in self.children]}
