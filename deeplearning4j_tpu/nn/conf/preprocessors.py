"""Input preprocessors: shape adapters auto-inserted between layer families.

Parity: reference ``nn/conf/preprocessor/`` (CnnToFeedForwardPreProcessor,
FeedForwardToCnnPreProcessor, RnnToFeedForwardPreProcessor,
FeedForwardToRnnPreProcessor, CnnToRnnPreProcessor, RnnToCnnPreProcessor).

Functional design: each preprocessor is a pure reshape/transpose on the
forward activations; the backward pass is derived by autodiff, so the
reference's hand-written ``backprop()`` methods have no analog here.
Mask transformation (``feedForwardMaskArray`` in the reference) is the
``transform_mask`` hook.

Layout note: CNN activations here are NHWC (TPU-native), so
CnnToFeedForward flattens in (h, w, c) order — this is recorded in the
serialized config so Keras/NCHW importers can insert permutations.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Type

import jax.numpy as jnp

from .inputs import InputType

_REGISTRY: Dict[str, Type["InputPreProcessor"]] = {}


def register_preprocessor(name: str):
    def deco(cls):
        cls._type_name = name
        _REGISTRY[name] = cls
        return cls
    return deco


def preprocessor_from_dict(d) -> "InputPreProcessor":
    d = dict(d)
    typ = d.pop("type")
    return _REGISTRY[typ](**d)


@dataclasses.dataclass(frozen=True)
class InputPreProcessor:
    _type_name = "base"

    def __call__(self, x, minibatch_size=None):
        raise NotImplementedError

    def transform_mask(self, mask, minibatch_size=None):
        return mask

    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def to_dict(self):
        return {"type": self._type_name, **dataclasses.asdict(self)}


@register_preprocessor("cnn_to_feedforward")
@dataclasses.dataclass(frozen=True)
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, minibatch_size=None):  # [b,h,w,c] -> [b, h*w*c]
        return x.reshape(x.shape[0], -1)

    def output_type(self, input_type):
        return InputType.feed_forward(input_type.height * input_type.width
                                      * input_type.channels)


@register_preprocessor("feedforward_to_cnn")
@dataclasses.dataclass(frozen=True)
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, minibatch_size=None):  # [b, h*w*c] -> [b,h,w,c]
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, input_type):
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preprocessor("rnn_to_feedforward")
@dataclasses.dataclass(frozen=True)
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[b, t, f] -> [b*t, f] (time-distributed dense).

    Parity: reference RnnToFeedForwardPreProcessor (which permutes an
    f-ordered NDArray; here a plain reshape has the same row semantics).
    """

    def __call__(self, x, minibatch_size=None):
        return x.reshape(-1, x.shape[-1])

    def transform_mask(self, mask, minibatch_size=None):
        return None if mask is None else mask.reshape(-1)

    def output_type(self, input_type):
        return InputType.feed_forward(input_type.size)


@register_preprocessor("feedforward_to_rnn")
@dataclasses.dataclass(frozen=True)
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    minibatch: int = 0  # optional serde-carried fallback; runtime passes minibatch_size

    def __call__(self, x, minibatch_size=None):
        b = minibatch_size if minibatch_size else self.minibatch
        if not b:
            raise ValueError(
                "FeedForwardToRnnPreProcessor needs minibatch_size to "
                "reconstruct the time axis from [b*t, f]; the network runtime "
                "supplies it — pass minibatch_size= when calling directly")
        return x.reshape(b, -1, x.shape[-1])

    def transform_mask(self, mask, minibatch_size=None):
        if mask is None:
            return None
        b = minibatch_size if minibatch_size else self.minibatch
        return mask.reshape(b, -1)

    def output_type(self, input_type):
        return InputType.recurrent(input_type.size)


@register_preprocessor("cnn_to_rnn")
@dataclasses.dataclass(frozen=True)
class CnnToRnnPreProcessor(InputPreProcessor):
    """[b*t, h, w, c] -> [b, t, h*w*c]."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, minibatch_size=None):
        b = minibatch_size or x.shape[0]
        return x.reshape(b, -1, self.height * self.width * self.channels)

    def output_type(self, input_type):
        return InputType.recurrent(input_type.height * input_type.width
                                   * input_type.channels)


@register_preprocessor("rnn_to_cnn")
@dataclasses.dataclass(frozen=True)
class RnnToCnnPreProcessor(InputPreProcessor):
    """[b, t, h*w*c] -> [b*t, h, w, c]."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, minibatch_size=None):
        return x.reshape(-1, self.height, self.width, self.channels)

    def transform_mask(self, mask, minibatch_size=None):
        return None if mask is None else mask.reshape(-1)

    def output_type(self, input_type):
        return InputType.convolutional(self.height, self.width, self.channels)
