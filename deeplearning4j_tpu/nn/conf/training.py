"""Training-level configuration (updater, LR schedule, grad normalization).

Parity: the training-relevant fields of reference NeuralNetConfiguration
(optimizationAlgo ``:506``, learningRate ``:484``, iterations, seed, updater +
per-updater hyperparams) and the schedule/normalization modes handled in
``nn/updater/LayerUpdater.java:132-226``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional


@dataclasses.dataclass
class TrainingConfig:
    seed: int = 12345
    iterations: int = 1                     # numIterations per minibatch (ref default 1)
    optimization_algo: str = "stochastic_gradient_descent"
    updater: str = "sgd"                    # sgd|adam|nesterovs|adagrad|rmsprop|adadelta|adamax|nadam|none
    learning_rate: float = 1e-1             # ref NeuralNetConfiguration.java:484
    momentum: float = 0.9
    rms_decay: float = 0.95
    rho: float = 0.95                       # adadelta
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    epsilon: float = 1e-8
    regularization: bool = False
    minibatch: bool = True
    max_line_search_iterations: int = 5
    # LR schedule (parity: LayerUpdater.java:132-155 LearningRatePolicy)
    lr_policy: str = "none"                 # none|exponential|inverse|step|torch_step|poly|sigmoid|schedule
    lr_policy_decay_rate: float = 0.0
    lr_policy_steps: float = 0.0
    lr_policy_power: float = 0.0
    lr_schedule: Optional[Dict[int, float]] = None
    # gradient normalization (parity: LayerUpdater.java:179-226)
    gradient_normalization: Optional[str] = None
    # renormalize_l2_per_layer | renormalize_l2_per_param_type |
    # clip_elementwise_absolute_value | clip_l2_per_layer | clip_l2_per_param_type
    gradient_normalization_threshold: float = 1.0
    dtype: str = "float32"                  # dtype policy name (dtypes.policy_from_name)
    # rematerialization: recompute layer activations in the backward pass
    # (jax.checkpoint per layer) — trades ~1/3 more FLOPs for activation
    # memory, the TPU-native answer when a batch/model OOMs HBM
    gradient_checkpointing: bool = False

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if d.get("lr_schedule"):
            d["lr_schedule"] = {str(k): v for k, v in d["lr_schedule"].items()}
        return d

    @staticmethod
    def from_dict(d: dict) -> "TrainingConfig":
        d = dict(d)
        if d.get("lr_schedule"):
            d["lr_schedule"] = {int(k): float(v) for k, v in d["lr_schedule"].items()}
        known = {f.name for f in dataclasses.fields(TrainingConfig)}
        return TrainingConfig(**{k: v for k, v in d.items() if k in known})
