"""Configuration DSL: serializable layer/network configs with shape inference.

Parity target: reference ``nn/conf/`` (NeuralNetConfiguration.Builder,
MultiLayerConfiguration, ComputationGraphConfiguration, layer configs,
InputType-driven nIn inference and automatic preprocessor insertion).
"""

from .inputs import InputType
from .builders import NeuralNetConfiguration, ListBuilder
from .multi_layer import MultiLayerConfiguration
from . import attention as _attention  # noqa: F401  (serde registration)
from . import moe as _moe  # noqa: F401  (serde registration)

__all__ = [
    "InputType", "NeuralNetConfiguration", "ListBuilder", "MultiLayerConfiguration",
]
