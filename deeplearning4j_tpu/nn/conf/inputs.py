"""Input types for shape inference.

Parity: reference ``nn/conf/inputs/InputType.java`` — FF / recurrent /
convolutional / convolutionalFlat. Drives nIn inference and automatic
preprocessor insertion (reference ``MultiLayerConfiguration.java:370-409``).

TPU-first note: image tensors are **NHWC** (channels-last) throughout this
framework — the layout XLA:TPU prefers — whereas the reference is NCHW.
InputType.convolutional(height, width, channels) therefore describes an
activations tensor of shape [batch, height, width, channels].
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class InputType:
    kind: str  # "feedforward" | "recurrent" | "convolutional" | "convolutional_flat"
    size: int = 0               # feedforward/recurrent feature size
    timesteps: Optional[int] = None  # recurrent (None = variable)
    height: int = 0
    width: int = 0
    channels: int = 0

    # -- factories (parity with InputType.feedForward(...) etc.) --
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="feedforward", size=size)

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType(kind="recurrent", size=size, timesteps=timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutional", height=height, width=width,
                         channels=channels)

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="convolutional_flat", size=height * width * channels,
                         height=height, width=width, channels=channels)

    def flat_size(self) -> int:
        if self.kind in ("feedforward", "recurrent", "convolutional_flat"):
            return self.size
        return self.height * self.width * self.channels

    def to_dict(self):
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v not in (None, 0) or k == "kind"}

    @staticmethod
    def from_dict(d) -> "InputType":
        return InputType(**{k: d.get(k, InputType.__dataclass_fields__[k].default)
                            for k in InputType.__dataclass_fields__})
