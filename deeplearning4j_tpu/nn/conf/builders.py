"""NeuralNetConfiguration builder — the user-facing config DSL.

Parity: reference ``nn/conf/NeuralNetConfiguration.java:479-`` (Builder with
global defaults: weightInit=XAVIER ``:481``, activation="sigmoid" ``:480``,
learningRate=1e-1 ``:484``, optimizationAlgo=STOCHASTIC_GRADIENT_DESCENT
``:506``), ``.list()`` ``:583`` and ``.graphBuilder()`` ``:613``.

Usage (mirrors the reference's fluent style):

    conf = (NeuralNetConfiguration.builder()
            .seed(123)
            .updater("adam").learning_rate(1e-3)
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())

Global defaults fill any per-layer field left as None (the reference does the
same by cloning builder globals into each layer config).
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from ..weights import Distribution
from .inputs import InputType
from .layers import Layer
from .preprocessors import InputPreProcessor
from .training import TrainingConfig


class NeuralNetConfiguration:
    """Namespace for the builder entrypoint (parity with the Java class)."""

    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    def __init__(self):
        self._t = TrainingConfig()
        # global layer defaults (applied to layers leaving fields None)
        self._defaults = dict(
            activation="sigmoid", weight_init="XAVIER", bias_init=0.0,
            dropout=0.0, l1=0.0, l2=0.0, dist=None,
            learning_rate=None, bias_learning_rate=None,
        )

    # ---- training-level settings ----
    def seed(self, s: int) -> "Builder":
        self._t.seed = int(s); return self

    def iterations(self, n: int) -> "Builder":
        self._t.iterations = int(n); return self

    def optimization_algo(self, algo: str) -> "Builder":
        self._t.optimization_algo = algo.lower(); return self

    def updater(self, name: str, **hyper) -> "Builder":
        self._t.updater = name.lower()
        for k, v in hyper.items():
            setattr(self._t, k, v)
        return self

    def learning_rate(self, lr: float) -> "Builder":
        self._t.learning_rate = float(lr); return self

    def bias_learning_rate(self, lr: float) -> "Builder":
        self._defaults["bias_learning_rate"] = float(lr); return self

    def momentum(self, m: float) -> "Builder":
        self._t.momentum = float(m); return self

    def rms_decay(self, d: float) -> "Builder":
        self._t.rms_decay = float(d); return self

    def rho(self, r: float) -> "Builder":
        self._t.rho = float(r); return self

    def adam_mean_decay(self, b1: float) -> "Builder":
        self._t.adam_beta1 = float(b1); return self

    def adam_var_decay(self, b2: float) -> "Builder":
        self._t.adam_beta2 = float(b2); return self

    def epsilon(self, e: float) -> "Builder":
        self._t.epsilon = float(e); return self

    def learning_rate_policy(self, policy: str, decay_rate: float = 0.0,
                             steps: float = 0.0, power: float = 0.0,
                             schedule: Optional[Dict[int, float]] = None) -> "Builder":
        self._t.lr_policy = policy.lower()
        self._t.lr_policy_decay_rate = decay_rate
        self._t.lr_policy_steps = steps
        self._t.lr_policy_power = power
        self._t.lr_schedule = schedule
        return self

    def gradient_normalization(self, kind: str, threshold: float = 1.0) -> "Builder":
        self._t.gradient_normalization = kind.lower()
        self._t.gradient_normalization_threshold = float(threshold)
        return self

    def gradient_checkpointing(self, flag: bool = True) -> "Builder":
        """Rematerialize per-layer activations in the backward pass
        (jax.checkpoint): ~1/3 more FLOPs for O(sqrt)-ish activation memory
        — enables batches/models that otherwise OOM HBM."""
        self._t.gradient_checkpointing = bool(flag)
        return self

    def max_num_line_search_iterations(self, n: int) -> "Builder":
        self._t.max_line_search_iterations = int(n); return self

    def minibatch(self, flag: bool) -> "Builder":
        self._t.minibatch = bool(flag); return self

    def dtype(self, policy_name: str) -> "Builder":
        self._t.dtype = policy_name; return self

    # ---- per-layer global defaults ----
    def activation(self, a: str) -> "Builder":
        self._defaults["activation"] = a; return self

    def weight_init(self, w: str) -> "Builder":
        self._defaults["weight_init"] = w.upper(); return self

    def bias_init(self, b: float) -> "Builder":
        self._defaults["bias_init"] = float(b); return self

    def dist(self, d: Distribution) -> "Builder":
        self._defaults["dist"] = d; return self

    def drop_out(self, d: float) -> "Builder":
        self._defaults["dropout"] = float(d); return self

    def l1(self, v: float) -> "Builder":
        self._defaults["l1"] = float(v); return self

    def l2(self, v: float) -> "Builder":
        self._defaults["l2"] = float(v); return self

    def regularization(self, flag: bool) -> "Builder":
        self._t.regularization = bool(flag); return self

    # ---- transitions ----
    def list(self) -> "ListBuilder":
        return ListBuilder(self)

    def graph_builder(self):
        from .graph import GraphBuilder
        return GraphBuilder(self)

    # internal: fill a layer's None fields with the global defaults
    def _apply_defaults(self, layer: Layer) -> Layer:
        layer = copy.deepcopy(layer)
        for field, val in self._defaults.items():
            if getattr(layer, field, "missing") is None and val is not None:
                setattr(layer, field, val)
        return layer


class ListBuilder:
    """Parity: NeuralNetConfiguration.ListBuilder →
    MultiLayerConfiguration.Builder (reference ``:583``)."""

    def __init__(self, base: Builder):
        self._base = base
        self._layers: List[Layer] = []
        self._preprocessors: Dict[int, InputPreProcessor] = {}
        self._input_type: Optional[InputType] = None
        self._backprop = True
        self._pretrain = False
        self._tbptt_fwd = 20
        self._tbptt_back = 20
        self._backprop_type = "standard"  # "standard" | "truncated_bptt"

    def layer(self, layer_or_idx, maybe_layer=None) -> "ListBuilder":
        if maybe_layer is None:
            self._layers.append(layer_or_idx)
        else:
            idx = int(layer_or_idx)
            while len(self._layers) <= idx:
                self._layers.append(None)
            self._layers[idx] = maybe_layer
        return self

    def input_preprocessor(self, idx: int, preproc: InputPreProcessor) -> "ListBuilder":
        self._preprocessors[idx] = preproc
        return self

    def set_input_type(self, input_type: InputType) -> "ListBuilder":
        self._input_type = input_type
        return self

    def backprop(self, flag: bool) -> "ListBuilder":
        self._backprop = bool(flag); return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._pretrain = bool(flag); return self

    def backprop_type(self, kind: str) -> "ListBuilder":
        self._backprop_type = kind.lower(); return self

    def t_bptt_forward_length(self, n: int) -> "ListBuilder":
        self._tbptt_fwd = int(n); return self

    def t_bptt_backward_length(self, n: int) -> "ListBuilder":
        self._tbptt_back = int(n); return self

    def build(self) -> "MultiLayerConfiguration":
        from .multi_layer import MultiLayerConfiguration

        if any(l is None for l in self._layers):
            raise ValueError("layer indices have gaps")
        layers = [self._base._apply_defaults(l) for l in self._layers]
        preprocessors = dict(self._preprocessors)

        # InputType-driven nIn inference + automatic preprocessor insertion
        # (parity: MultiLayerConfiguration.Builder.build →
        #  reference MultiLayerConfiguration.java:370-409)
        input_type = self._input_type
        if input_type is not None:
            cur = input_type
            for i, layer in enumerate(layers):
                proc = preprocessors.get(i) or layer.preprocessor_for(cur)
                if proc is not None:
                    preprocessors[i] = proc
                    cur = proc.output_type(cur)
                layer.set_n_in(cur, override=False)
                cur = layer.output_type(cur)

        return MultiLayerConfiguration(
            layers=layers,
            input_preprocessors=preprocessors,
            training=copy.deepcopy(self._base._t),
            input_type=input_type,
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
        )
