"""Pretrain layers: denoising AutoEncoder + RBM.

Parity: reference ``nn/conf/layers/AutoEncoder.java`` / ``RBM.java`` (config)
and runtime ``nn/layers/feedforward/autoencoder/AutoEncoder.java``
(corruption + tied-weight reconstruction) / ``rbm/RBM.java:100``
(``contrastiveDivergence``, Gibbs chain ``:192``), plus
``PretrainParamInitializer`` (W, hidden bias b, visible bias vb).

TPU-native: the CD-k Gibbs chain is a ``lax.scan`` inside one jitted pretrain
step; reconstruction/CD gradients come from ``jax.grad`` (for AE) or the
explicit positive−negative phase statistics (for RBM — CD is not a true
gradient, so it is written out, batched, as matmuls).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ... import dtypes as _dtypes
from ..weights import init_weights
from .inputs import InputType
from .layers import FeedForwardLayer, register_layer


@dataclasses.dataclass
class BasePretrainLayer(FeedForwardLayer):
    """Shared params: W [n_in, n_out], hidden bias b, visible bias vb
    (parity: ``PretrainParamInitializer``)."""

    loss: str = "mse"   # reconstruction loss: mse | xent

    def param_shapes(self, policy=None):
        return {"W": (self.n_in, self.n_out), "b": (self.n_out,),
                "vb": (self.n_in,)}

    def init_params(self, key, policy=None):
        policy = policy or _dtypes.default_policy()
        dt = policy.param_dtype
        w = init_weights(key, (self.n_in, self.n_out),
                         self.weight_init or "XAVIER", fan_in=self.n_in,
                         fan_out=self.n_out, distribution=self.dist, dtype=dt)
        return {"W": w, "b": jnp.zeros((self.n_out,), dt),
                "vb": jnp.zeros((self.n_in,), dt)}

    # encoder forward (used when stacked inside a network)
    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        policy = policy or _dtypes.default_policy()
        x = self._dropout_in(x, train, rng)
        xc, wc = policy.cast_to_compute(x, params["W"])
        z = xc @ wc + params["b"].astype(xc.dtype)
        return self._act()(z), state

    def reconstruction_error(self, params, x, *, policy=None) -> jax.Array:
        """Mean reconstruction loss on a batch (no corruption)."""
        h, _ = self.apply(params, x, policy=policy)
        return self._recon_loss(params, h, x)

    def _decode(self, params, h):
        return h @ params["W"].T.astype(h.dtype) + params["vb"].astype(h.dtype)

    def _recon_loss(self, params, h, x):
        z = self._decode(params, h)
        if self.loss == "xent":
            # sigmoid cross-entropy against inputs in [0,1], stable logit form
            return jnp.mean(jnp.sum(
                jnp.maximum(z, 0) - z * x + jnp.log1p(jnp.exp(-jnp.abs(z))),
                axis=-1))
        recon = self._act()(z) if self.loss == "mse_act" else z
        return jnp.mean(jnp.sum((recon - x) ** 2, axis=-1))


@register_layer("autoencoder")
@dataclasses.dataclass
class AutoEncoder(BasePretrainLayer):
    """Denoising autoencoder (parity: ``AutoEncoder.java`` —
    ``corruptionLevel`` masking noise, tied-weight decode)."""

    corruption_level: float = 0.3

    def pretrain_loss(self, params, x, rng, *, policy=None) -> jax.Array:
        policy = policy or _dtypes.default_policy()
        if self.corruption_level > 0.0 and rng is not None:
            keep = jax.random.bernoulli(
                rng, 1.0 - self.corruption_level, x.shape)
            x_in = x * keep.astype(x.dtype)
        else:
            x_in = x
        h, _ = self.apply(params, x_in, policy=policy)
        return self._recon_loss(params, h, x)


@register_layer("rbm")
@dataclasses.dataclass
class RBM(BasePretrainLayer):
    """Restricted Boltzmann machine (parity: ``RBM.java`` — binary/gaussian
    units, CD-k via Gibbs chain)."""

    hidden_unit: str = "binary"    # binary | rectified
    visible_unit: str = "binary"   # binary | gaussian
    k: int = 1                     # CD-k Gibbs steps

    def prop_up(self, params, v):
        return jax.nn.sigmoid(v @ params["W"].astype(v.dtype)
                              + params["b"].astype(v.dtype))

    def prop_down(self, params, h):
        z = self._decode(params, h)
        if self.visible_unit == "gaussian":
            return z
        return jax.nn.sigmoid(z)

    def _sample_h(self, params, v, rng):
        p = self.prop_up(params, v)
        if self.hidden_unit == "rectified":
            return jnp.maximum(p, 0.0), p
        return jax.random.bernoulli(rng, p).astype(v.dtype), p

    def _sample_v(self, params, h, rng):
        p = self.prop_down(params, h)
        if self.visible_unit == "gaussian":
            return p + jax.random.normal(rng, p.shape, p.dtype), p
        return jax.random.bernoulli(rng, p).astype(h.dtype), p

    def contrastive_divergence_grads(self, params, v0, rng):
        """CD-k statistics → (pseudo-)gradients for W, b, vb
        (parity: ``RBM.contrastiveDivergence`` :100, Gibbs :192)."""
        h0_sample, h0_prob = self._sample_h(params, v0, jax.random.fold_in(rng, 0))

        def gibbs(carry, i):
            h_sample = carry
            v_sample, _ = self._sample_v(params, h_sample,
                                         jax.random.fold_in(rng, 2 * i + 1))
            h_next, h_prob = self._sample_h(params, v_sample,
                                            jax.random.fold_in(rng, 2 * i + 2))
            return h_next, (v_sample, h_prob)

        _, (v_chain, h_chain) = jax.lax.scan(
            gibbs, h0_sample, jnp.arange(self.k))
        vk, hk_prob = v_chain[-1], h_chain[-1]
        n = v0.shape[0]
        gW = -(v0.T @ h0_prob - vk.T @ hk_prob) / n
        gb = -jnp.mean(h0_prob - hk_prob, axis=0)
        gvb = -jnp.mean(v0 - vk, axis=0)
        return {"W": gW, "b": gb, "vb": gvb}

    def free_energy(self, params, v) -> jax.Array:
        """Mean free energy (monitoring; parity: RBM.freeEnergy)."""
        wx_b = v @ params["W"].astype(v.dtype) + params["b"].astype(v.dtype)
        vbias_term = v @ params["vb"].astype(v.dtype)
        hidden_term = jnp.sum(jax.nn.softplus(wx_b), axis=-1)
        return -jnp.mean(hidden_term + vbias_term)


def make_pretrain_step(layer, lr: float, policy=None):
    """Jitted one-batch pretrain update for a pretrainable layer — CD-k for
    RBMs, reconstruction-loss SGD for autoencoders. The single definition
    shared by MultiLayerNetwork.pretrain and ComputationGraph.pretrain."""
    if hasattr(layer, "contrastive_divergence_grads"):
        @jax.jit
        def step(lparams, v, rng):
            grads = layer.contrastive_divergence_grads(lparams, v, rng)
            return jax.tree_util.tree_map(
                lambda p, g: p - lr * g.astype(p.dtype), lparams, grads)
        return step

    @jax.jit
    def step(lparams, x, rng):
        grads = jax.grad(
            lambda p: layer.pretrain_loss(p, x, rng, policy=policy))(lparams)
        return jax.tree_util.tree_map(
            lambda p, g: p - lr * g.astype(p.dtype), lparams, grads)
    return step
