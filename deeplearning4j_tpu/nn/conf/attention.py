"""Multi-head self-attention as a config-DSL layer.

The reference has NO attention layer (LSTM era — SURVEY §2.9); this is the
long-context north-star extension surfaced in the same builder DSL as every
other layer, so sequence models can mix attention with the reference layer
set. Works on recurrent activations [b, t, f]; honours sequence masks the
same way the recurrent layers do (masked keys are not attended, masked
steps output 0).

The single-device path uses the fused ``ops.attention.dot_product_attention``;
inside an ``ops.attention.sequence_sharding`` context (entered by
``parallel.sequence.SequenceParallelGraphTrainer`` around its step trace)
the same math runs as ring attention over the sequence-sharded mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ... import dtypes as _dtypes
from .inputs import InputType
from .layers import Layer, register_layer
from ..weights import init_weights


@register_layer("self_attention")
@dataclasses.dataclass
class SelfAttentionLayer(Layer):
    """Causal/bidirectional multi-head self-attention with output projection.

    Params: fused qkv projection ``Wqkv`` [n_in, 3·n_in], output projection
    ``Wo`` [n_in, n_out], bias ``b`` [n_out]. ``n_in`` must divide by
    ``n_heads``.
    """

    n_in: Optional[int] = None
    n_out: Optional[int] = None       # defaults to n_in
    n_heads: int = 4
    causal: bool = True
    # streaming decode: K/V cache length for rnn_time_step. None = no
    # cache — rnn_time_step then attends WITHIN each fed chunk only (no
    # history), which is almost never what you want for attention; set
    # max_cache_t for true incremental decode. Feeding more than
    # max_cache_t TOTAL steps slides the window: the OLDEST cached
    # positions are evicted (positions stay global, so the causal masks
    # remain correct) and the runtimes emit a RuntimeWarning at the first
    # overflow (util.netutil.note_streamed_steps) — reset with
    # rnn_clear_previous_state() between sequences. Causal layers only.
    max_cache_t: Optional[int] = None
    # what overflowing max_cache_t means: "evict" = sliding-window
    # attention over the most recent max_cache_t positions (the default,
    # and what the paged serving arena does page-at-a-time); "strict" =
    # the runtimes raise util.netutil.StreamingCacheOverflow host-side
    # BEFORE the overflowing dispatch (for callers whose correctness
    # depends on full history)
    cache_overflow: str = "evict"

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out or self.n_in,
                                   input_type.timesteps)

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        if self.n_in is None or override:
            self.n_in = input_type.flat_size()
        if self.n_out is None:
            self.n_out = self.n_in
        if self.n_in % self.n_heads:
            raise ValueError(f"n_in={self.n_in} not divisible by "
                             f"n_heads={self.n_heads}")

    def preprocessor_for(self, input_type: InputType):
        # same adapters the recurrent layers insert (BaseRecurrentLayer)
        from .preprocessors import (CnnToRnnPreProcessor,
                                    FeedForwardToRnnPreProcessor)
        if input_type.kind == "feedforward":
            return FeedForwardToRnnPreProcessor()
        if input_type.kind == "convolutional":
            return CnnToRnnPreProcessor(height=input_type.height,
                                        width=input_type.width,
                                        channels=input_type.channels)
        return None

    def has_params(self) -> bool:
        return True

    def param_shapes(self, policy=None) -> Dict[str, Tuple[int, ...]]:
        return {"Wqkv": (self.n_in, 3 * self.n_in),
                "Wo": (self.n_in, self.n_out), "b": (self.n_out,)}

    def regularized_params(self):
        return ("Wqkv", "Wo")

    def init_params(self, key, policy=None):
        policy = policy or _dtypes.default_policy()
        dt = policy.param_dtype
        k1, k2 = jax.random.split(key)
        wqkv = init_weights(k1, (self.n_in, 3 * self.n_in),
                            self.weight_init or "XAVIER",
                            fan_in=self.n_in, fan_out=self.n_in,
                            distribution=self.dist, dtype=dt)
        wo = init_weights(k2, (self.n_in, self.n_out),
                          self.weight_init or "XAVIER",
                          fan_in=self.n_in, fan_out=self.n_out,
                          distribution=self.dist, dtype=dt)
        return {"Wqkv": wqkv, "Wo": wo,
                "b": jnp.full((self.n_out,), float(self.bias_init or 0.0),
                              dt)}

    def _zero_state(self, batch, policy):
        """Streaming K/V cache (only when ``max_cache_t`` is set): rides
        the same h/c carry machinery as the recurrent layers —
        ``h``/``c`` are the [b, max_t+1, n_in] K/V caches whose LAST row
        smuggles the write position (the carry contract is h/c-shaped,
        so the counter lives in-band)."""
        if self.max_cache_t is None:
            raise ValueError(
                "SelfAttentionLayer streaming needs max_cache_t set")
        if self.cache_overflow not in ("evict", "strict"):
            raise ValueError(
                f"cache_overflow={self.cache_overflow!r} — expected "
                "'evict' or 'strict'")
        if not self.causal:
            raise ValueError(
                "SelfAttentionLayer streaming decode requires causal=True "
                "(incremental decode of bidirectional attention is "
                "ill-defined — later tokens would change earlier outputs)")
        # at least f32: the in-band position counter must count exactly
        # (bf16 rounds integers past 256), and cached K/V precision
        # benefits too
        dt = jnp.promote_types(policy.compute_dtype, jnp.float32)
        shape = (batch, self.max_cache_t + 1, self.n_in)
        return jnp.zeros(shape, dt), jnp.zeros(shape, dt)

    def _apply_streaming(self, params, xc, state, policy):
        """Incremental decode: append this chunk's K/V to the cache and
        attend the new queries over everything cached so far (causal
        across calls). O(t_new · cached) instead of O(T²) per token.

        Overflow is sliding-window EVICTION: once the fed total exceeds
        ``max_cache_t`` the oldest cached positions are rolled out, so
        the cache always holds the most recent ``max_cache_t`` tokens.
        Positions stay GLOBAL — the in-band counter keeps counting fed
        steps and the causal mask is computed in view-relative terms
        (slot j holds global position ``base + j``).

        Eviction is CHUNK-granular: the whole chunk's worth of old
        positions is evicted before any of the chunk's queries attend,
        so in an overflowing multi-step chunk query i sees
        ``max_cache_t - (t_new - 1 - i)`` back-positions, not the full
        window (the chunk's LAST query always sees exactly
        ``(p - max_cache_t, p]``). Token-by-token decode (t_new=1 — the
        decode loops' shape) therefore gets the exact per-token sliding
        window; callers that need it for long prompts feed the
        over-window tail in single steps (``models.transformer.
        generate`` does). The paged serving arena makes the matching
        choice at page granularity. Below the window this is a no-op
        (shift 0) and the math is bit-identical to the pre-eviction
        path."""
        b, t_new, f = xc.shape
        h = self.n_heads
        max_t = self.max_cache_t
        if t_new > max_t:   # shapes are static: fail at trace, not silently
            raise ValueError(
                f"streaming chunk of {t_new} steps exceeds "
                f"max_cache_t={max_t}; raise max_cache_t or feed smaller "
                "chunks")
        wqkv = params["Wqkv"].astype(xc.dtype)
        qkv = (xc @ wqkv).reshape(b, t_new, 3, h, f // h)
        q, k_new, v_new = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        k_cache, v_cache = state["h"], state["c"]
        pos = k_cache[0, -1, 0].astype(jnp.int32)
        # cache slot j holds global position base + j; this call may
        # advance base (evict) so the t_new new tokens fit at the end
        old_base = jnp.maximum(pos - max_t, 0)
        new_base = jnp.maximum(pos + t_new - max_t, 0)
        shift = new_base - old_base            # positions evicted now
        write_pos = pos - new_base             # == min(pos, max_t - t_new)
        # the roll is a whole-window gather — only pay it on the calls
        # that actually evict (shift stays 0 until the window fills)
        body_k, body_v = jax.lax.cond(
            shift > 0,
            lambda kv: (jnp.roll(kv[0], -shift, axis=1),
                        jnp.roll(kv[1], -shift, axis=1)),
            lambda kv: kv,
            (k_cache[:, :max_t], v_cache[:, :max_t]))
        k_flat = k_new.reshape(b, t_new, f).astype(k_cache.dtype)
        v_flat = v_new.reshape(b, t_new, f).astype(v_cache.dtype)
        zero = jnp.zeros((), pos.dtype)
        body_k = jax.lax.dynamic_update_slice(body_k, k_flat,
                                              (zero, write_pos, zero))
        body_v = jax.lax.dynamic_update_slice(body_v, v_flat,
                                              (zero, write_pos, zero))
        kh = body_k.reshape(b, max_t, h, f // h)
        vh = body_v.reshape(b, max_t, h, f // h)
        scale = 1.0 / jnp.sqrt(f // h).astype(xc.dtype)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kh) * scale
        # new query i sits at global position pos+i = view slot
        # write_pos+i: attend view slots <= write_pos+i (evicted
        # positions are simply absent from the view)
        key_idx = jnp.arange(max_t)
        q_idx = write_pos + jnp.arange(t_new)
        allow = key_idx[None, :] <= q_idx[:, None]          # [t_new, max_t]
        logits = jnp.where(allow[None, None], logits.astype(jnp.float32),
                           -jnp.inf)
        m = jnp.max(logits, axis=-1, keepdims=True)
        m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
        p = jnp.where(jnp.isneginf(logits), 0.0, jnp.exp(logits - m_safe))
        weights = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True),
                                  1e-30)
        att = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(xc.dtype), vh)
        wo = params["Wo"].astype(att.dtype)
        out = att.reshape(b, t_new, f) @ wo + params["b"].astype(att.dtype)
        out = self._act(self.activation or "identity")(out)
        new_pos = (pos + t_new).astype(k_cache.dtype)
        k_cache = jnp.concatenate(
            [body_k, k_cache[:, max_t:].at[:, 0, 0].set(new_pos)], axis=1)
        v_cache = jnp.concatenate(
            [body_v, v_cache[:, max_t:].at[:, 0, 0].set(new_pos)], axis=1)
        return out, {"h": k_cache, "c": v_cache}

    def apply_paged(self, params, x, k_pool, v_pool, page_table,
                    write_slots, rel_pos, *, policy=None):
        """Paged-arena streaming decode (the serving continuous-batching
        path): K/V live in shared ``[num_pages, page_size, h, d]`` block
        pools instead of a per-sequence dense cache; each lane's page
        table reassembles its window by gather. The math mirrors
        :meth:`_apply_streaming` exactly — ``tests/test_decode.py`` pins
        greedy decode through the arena bit-exact against the dense
        full-cache path for sequences within the window. Sliding-window
        overflow is PAGE eviction, done host-side by the serving engine
        (page table shifts, ``rel_pos`` stays put); positions stay
        global throughout, but past the window the paged and dense
        paths legitimately differ by eviction granularity (a page vs a
        token at a time).

        x: ``[S, t_new, f]`` raw input activations; write_slots:
        ``[S, t_new]`` view-relative write slots (-1 = padded, dropped);
        rel_pos: ``[S]`` view-relative position of the first new query.
        Returns ``(out, k_pool, v_pool)``.
        """
        from ...ops.paged_attention import (paged_attention, paged_gather,
                                            paged_write)
        policy = policy or _dtypes.default_policy()
        xc, wqkv = policy.cast_to_compute(x, params["Wqkv"])
        b, t_new, f = xc.shape
        h = self.n_heads
        qkv = (xc @ wqkv).reshape(b, t_new, 3, h, f // h)
        q, k_new, v_new = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        k_pool = paged_write(k_pool, k_new, page_table, write_slots)
        v_pool = paged_write(v_pool, v_new, page_table, write_slots)
        kh = paged_gather(k_pool, page_table)
        vh = paged_gather(v_pool, page_table)
        scale = 1.0 / jnp.sqrt(f // h).astype(xc.dtype)
        att = paged_attention(q, kh, vh, rel_pos, scale)
        wo = params["Wo"].astype(att.dtype)
        out = att.reshape(b, t_new, f) @ wo + params["b"].astype(att.dtype)
        out = self._act(self.activation or "identity")(out)
        return out, k_pool, v_pool

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        from ...ops.attention import (active_sequence_sharding,
                                      dot_product_attention,
                                      make_ring_attention)
        policy = policy or _dtypes.default_policy()
        x = self._dropout_in(x, train, rng)
        xc, wqkv = policy.cast_to_compute(x, params["Wqkv"])
        if (not train and mask is None and self.max_cache_t is not None
                and state is not None and "h" in state):
            # streaming decode with the carried K/V cache (rnn_time_step)
            return self._apply_streaming(params, xc, state, policy)
        b, t, f = xc.shape
        h = self.n_heads
        qkv = (xc @ wqkv).reshape(b, t, 3, h, f // h)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        seq_ctx = active_sequence_sharding()
        if seq_ctx is not None:
            # sequence-parallel route: the time axis is sharded over the
            # mesh — the one op that mixes timesteps runs as ring attention
            # (K/V shards rotate over ppermute; see parallel/sequence.py).
            # Key masks ride the ring too: each mask shard rotates with
            # its K/V shard.
            mesh, seq_axis, batch_axis = seq_ctx
            if mask is None:
                ring = make_ring_attention(mesh, seq_axis,
                                           causal=self.causal,
                                           batch_axis=batch_axis)
                att = ring(q, k, v)
            else:
                ring = make_ring_attention(mesh, seq_axis,
                                           causal=self.causal,
                                           batch_axis=batch_axis,
                                           with_mask=True)
                att = ring(q, k, v, mask)
        else:
            att = dot_product_attention(q, k, v, causal=self.causal,
                                        mask=mask)
        wo = params["Wo"].astype(att.dtype)
        out = att.reshape(b, t, f) @ wo + params["b"].astype(att.dtype)
        out = self._act(self.activation or "identity")(out)
        if mask is not None:
            out = out * mask[:, :, None].astype(out.dtype)
        return out, state
