"""Multi-head self-attention as a config-DSL layer.

The reference has NO attention layer (LSTM era — SURVEY §2.9); this is the
long-context north-star extension surfaced in the same builder DSL as every
other layer, so sequence models can mix attention with the reference layer
set. Works on recurrent activations [b, t, f]; honours sequence masks the
same way the recurrent layers do (masked keys are not attended, masked
steps output 0).

The single-device path uses the fused ``ops.attention.dot_product_attention``;
inside an ``ops.attention.sequence_sharding`` context (entered by
``parallel.sequence.SequenceParallelGraphTrainer`` around its step trace)
the same math runs as ring attention over the sequence-sharded mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ... import dtypes as _dtypes
from .inputs import InputType
from .layers import Layer, register_layer
from ..weights import init_weights


@register_layer("self_attention")
@dataclasses.dataclass
class SelfAttentionLayer(Layer):
    """Causal/bidirectional multi-head self-attention with output projection.

    Params: fused qkv projection ``Wqkv`` [n_in, 3·n_in], output projection
    ``Wo`` [n_in, n_out], bias ``b`` [n_out]. ``n_in`` must divide by
    ``n_heads``.
    """

    n_in: Optional[int] = None
    n_out: Optional[int] = None       # defaults to n_in
    n_heads: int = 4
    causal: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out or self.n_in,
                                   input_type.timesteps)

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        if self.n_in is None or override:
            self.n_in = input_type.flat_size()
        if self.n_out is None:
            self.n_out = self.n_in
        if self.n_in % self.n_heads:
            raise ValueError(f"n_in={self.n_in} not divisible by "
                             f"n_heads={self.n_heads}")

    def preprocessor_for(self, input_type: InputType):
        # same adapters the recurrent layers insert (BaseRecurrentLayer)
        from .preprocessors import (CnnToRnnPreProcessor,
                                    FeedForwardToRnnPreProcessor)
        if input_type.kind == "feedforward":
            return FeedForwardToRnnPreProcessor()
        if input_type.kind == "convolutional":
            return CnnToRnnPreProcessor(height=input_type.height,
                                        width=input_type.width,
                                        channels=input_type.channels)
        return None

    def has_params(self) -> bool:
        return True

    def param_shapes(self, policy=None) -> Dict[str, Tuple[int, ...]]:
        return {"Wqkv": (self.n_in, 3 * self.n_in),
                "Wo": (self.n_in, self.n_out), "b": (self.n_out,)}

    def regularized_params(self):
        return ("Wqkv", "Wo")

    def init_params(self, key, policy=None):
        policy = policy or _dtypes.default_policy()
        dt = policy.param_dtype
        k1, k2 = jax.random.split(key)
        wqkv = init_weights(k1, (self.n_in, 3 * self.n_in),
                            self.weight_init or "XAVIER",
                            fan_in=self.n_in, fan_out=self.n_in,
                            distribution=self.dist, dtype=dt)
        wo = init_weights(k2, (self.n_in, self.n_out),
                          self.weight_init or "XAVIER",
                          fan_in=self.n_in, fan_out=self.n_out,
                          distribution=self.dist, dtype=dt)
        return {"Wqkv": wqkv, "Wo": wo,
                "b": jnp.full((self.n_out,), float(self.bias_init or 0.0),
                              dt)}

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        from ...ops.attention import (active_sequence_sharding,
                                      dot_product_attention,
                                      make_ring_attention)
        policy = policy or _dtypes.default_policy()
        x = self._dropout_in(x, train, rng)
        xc, wqkv = policy.cast_to_compute(x, params["Wqkv"])
        b, t, f = xc.shape
        h = self.n_heads
        qkv = (xc @ wqkv).reshape(b, t, 3, h, f // h)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        seq_ctx = active_sequence_sharding()
        if seq_ctx is not None:
            # sequence-parallel route: the time axis is sharded over the
            # mesh — the one op that mixes timesteps runs as ring attention
            # (K/V shards rotate over ppermute; see parallel/sequence.py).
            # Key masks ride the ring too: each mask shard rotates with
            # its K/V shard.
            mesh, seq_axis, batch_axis = seq_ctx
            if mask is None:
                ring = make_ring_attention(mesh, seq_axis,
                                           causal=self.causal,
                                           batch_axis=batch_axis)
                att = ring(q, k, v)
            else:
                ring = make_ring_attention(mesh, seq_axis,
                                           causal=self.causal,
                                           batch_axis=batch_axis,
                                           with_mask=True)
                att = ring(q, k, v, mask)
        else:
            att = dot_product_attention(q, k, v, causal=self.causal,
                                        mask=mask)
        wo = params["Wo"].astype(att.dtype)
        out = att.reshape(b, t, f) @ wo + params["b"].astype(att.dtype)
        out = self._act(self.activation or "identity")(out)
        if mask is not None:
            out = out * mask[:, :, None].astype(out.dtype)
        return out, state
