"""Multi-head self-attention as a config-DSL layer.

The reference has NO attention layer (LSTM era — SURVEY §2.9); this is the
long-context north-star extension surfaced in the same builder DSL as every
other layer, so sequence models can mix attention with the reference layer
set. Works on recurrent activations [b, t, f]; honours sequence masks the
same way the recurrent layers do (masked keys are not attended, masked
steps output 0).

The single-device path uses the fused ``ops.attention.dot_product_attention``;
inside an ``ops.attention.sequence_sharding`` context (entered by
``parallel.sequence.SequenceParallelGraphTrainer`` around its step trace)
the same math runs as ring attention over the sequence-sharded mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ... import dtypes as _dtypes
from .inputs import InputType
from .layers import Layer, register_layer
from ..weights import init_weights


@register_layer("self_attention")
@dataclasses.dataclass
class SelfAttentionLayer(Layer):
    """Causal/bidirectional multi-head self-attention with output projection.

    Params: fused qkv projection ``Wqkv`` [n_in, 3·n_in], output projection
    ``Wo`` [n_in, n_out], bias ``b`` [n_out]. ``n_in`` must divide by
    ``n_heads``.
    """

    n_in: Optional[int] = None
    n_out: Optional[int] = None       # defaults to n_in
    n_heads: int = 4
    causal: bool = True
    # streaming decode: K/V cache length for rnn_time_step. None = no
    # cache — rnn_time_step then attends WITHIN each fed chunk only (no
    # history), which is almost never what you want for attention; set
    # max_cache_t for true incremental decode. Feeding more than
    # max_cache_t TOTAL steps clamps (the tail overwrites); the runtimes
    # count fed steps host-side and emit a RuntimeWarning at the first
    # overflow (util.netutil.note_streamed_steps) — reset with
    # rnn_clear_previous_state() between sequences. Causal layers only.
    max_cache_t: Optional[int] = None

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out or self.n_in,
                                   input_type.timesteps)

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        if self.n_in is None or override:
            self.n_in = input_type.flat_size()
        if self.n_out is None:
            self.n_out = self.n_in
        if self.n_in % self.n_heads:
            raise ValueError(f"n_in={self.n_in} not divisible by "
                             f"n_heads={self.n_heads}")

    def preprocessor_for(self, input_type: InputType):
        # same adapters the recurrent layers insert (BaseRecurrentLayer)
        from .preprocessors import (CnnToRnnPreProcessor,
                                    FeedForwardToRnnPreProcessor)
        if input_type.kind == "feedforward":
            return FeedForwardToRnnPreProcessor()
        if input_type.kind == "convolutional":
            return CnnToRnnPreProcessor(height=input_type.height,
                                        width=input_type.width,
                                        channels=input_type.channels)
        return None

    def has_params(self) -> bool:
        return True

    def param_shapes(self, policy=None) -> Dict[str, Tuple[int, ...]]:
        return {"Wqkv": (self.n_in, 3 * self.n_in),
                "Wo": (self.n_in, self.n_out), "b": (self.n_out,)}

    def regularized_params(self):
        return ("Wqkv", "Wo")

    def init_params(self, key, policy=None):
        policy = policy or _dtypes.default_policy()
        dt = policy.param_dtype
        k1, k2 = jax.random.split(key)
        wqkv = init_weights(k1, (self.n_in, 3 * self.n_in),
                            self.weight_init or "XAVIER",
                            fan_in=self.n_in, fan_out=self.n_in,
                            distribution=self.dist, dtype=dt)
        wo = init_weights(k2, (self.n_in, self.n_out),
                          self.weight_init or "XAVIER",
                          fan_in=self.n_in, fan_out=self.n_out,
                          distribution=self.dist, dtype=dt)
        return {"Wqkv": wqkv, "Wo": wo,
                "b": jnp.full((self.n_out,), float(self.bias_init or 0.0),
                              dt)}

    def _zero_state(self, batch, policy):
        """Streaming K/V cache (only when ``max_cache_t`` is set): rides
        the same h/c carry machinery as the recurrent layers —
        ``h``/``c`` are the [b, max_t+1, n_in] K/V caches whose LAST row
        smuggles the write position (the carry contract is h/c-shaped,
        so the counter lives in-band)."""
        if self.max_cache_t is None:
            raise ValueError(
                "SelfAttentionLayer streaming needs max_cache_t set")
        if not self.causal:
            raise ValueError(
                "SelfAttentionLayer streaming decode requires causal=True "
                "(incremental decode of bidirectional attention is "
                "ill-defined — later tokens would change earlier outputs)")
        # at least f32: the in-band position counter must count exactly
        # (bf16 rounds integers past 256), and cached K/V precision
        # benefits too
        dt = jnp.promote_types(policy.compute_dtype, jnp.float32)
        shape = (batch, self.max_cache_t + 1, self.n_in)
        return jnp.zeros(shape, dt), jnp.zeros(shape, dt)

    def _apply_streaming(self, params, xc, state, policy):
        """Incremental decode: append this chunk's K/V to the cache and
        attend the new queries over everything cached so far (causal
        across calls). O(t_new · cached) instead of O(T²) per token."""
        b, t_new, f = xc.shape
        h = self.n_heads
        max_t = self.max_cache_t
        if t_new > max_t:   # shapes are static: fail at trace, not silently
            raise ValueError(
                f"streaming chunk of {t_new} steps exceeds "
                f"max_cache_t={max_t}; raise max_cache_t or feed smaller "
                "chunks")
        wqkv = params["Wqkv"].astype(xc.dtype)
        qkv = (xc @ wqkv).reshape(b, t_new, 3, h, f // h)
        q, k_new, v_new = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        k_cache, v_cache = state["h"], state["c"]
        pos = k_cache[0, -1, 0].astype(jnp.int32)
        pos = jnp.minimum(pos, max_t - t_new)   # clamp (documented)
        k_flat = k_new.reshape(b, t_new, f).astype(k_cache.dtype)
        v_flat = v_new.reshape(b, t_new, f).astype(v_cache.dtype)
        zero = jnp.zeros((), pos.dtype)
        k_cache = jax.lax.dynamic_update_slice(k_cache, k_flat,
                                               (zero, pos, zero))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v_flat,
                                               (zero, pos, zero))
        kh = k_cache[:, :max_t].reshape(b, max_t, h, f // h)
        vh = v_cache[:, :max_t].reshape(b, max_t, h, f // h)
        scale = 1.0 / jnp.sqrt(f // h).astype(xc.dtype)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kh) * scale
        # new query i sits at global position pos+i: attend keys <= pos+i
        key_idx = jnp.arange(max_t)
        q_idx = pos + jnp.arange(t_new)
        allow = key_idx[None, :] <= q_idx[:, None]          # [t_new, max_t]
        logits = jnp.where(allow[None, None], logits.astype(jnp.float32),
                           -jnp.inf)
        m = jnp.max(logits, axis=-1, keepdims=True)
        m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
        p = jnp.where(jnp.isneginf(logits), 0.0, jnp.exp(logits - m_safe))
        weights = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True),
                                  1e-30)
        att = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(xc.dtype), vh)
        wo = params["Wo"].astype(att.dtype)
        out = att.reshape(b, t_new, f) @ wo + params["b"].astype(att.dtype)
        out = self._act(self.activation or "identity")(out)
        new_pos = (pos + t_new).astype(k_cache.dtype)
        k_cache = k_cache.at[:, -1, 0].set(new_pos)
        v_cache = v_cache.at[:, -1, 0].set(new_pos)
        return out, {"h": k_cache, "c": v_cache}

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        from ...ops.attention import (active_sequence_sharding,
                                      dot_product_attention,
                                      make_ring_attention)
        policy = policy or _dtypes.default_policy()
        x = self._dropout_in(x, train, rng)
        xc, wqkv = policy.cast_to_compute(x, params["Wqkv"])
        if (not train and mask is None and self.max_cache_t is not None
                and state is not None and "h" in state):
            # streaming decode with the carried K/V cache (rnn_time_step)
            return self._apply_streaming(params, xc, state, policy)
        b, t, f = xc.shape
        h = self.n_heads
        qkv = (xc @ wqkv).reshape(b, t, 3, h, f // h)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        seq_ctx = active_sequence_sharding()
        if seq_ctx is not None:
            # sequence-parallel route: the time axis is sharded over the
            # mesh — the one op that mixes timesteps runs as ring attention
            # (K/V shards rotate over ppermute; see parallel/sequence.py).
            # Key masks ride the ring too: each mask shard rotates with
            # its K/V shard.
            mesh, seq_axis, batch_axis = seq_ctx
            if mask is None:
                ring = make_ring_attention(mesh, seq_axis,
                                           causal=self.causal,
                                           batch_axis=batch_axis)
                att = ring(q, k, v)
            else:
                ring = make_ring_attention(mesh, seq_axis,
                                           causal=self.causal,
                                           batch_axis=batch_axis,
                                           with_mask=True)
                att = ring(q, k, v, mask)
        else:
            att = dot_product_attention(q, k, v, causal=self.causal,
                                        mask=mask)
        wo = params["Wo"].astype(att.dtype)
        out = att.reshape(b, t, f) @ wo + params["b"].astype(att.dtype)
        out = self._act(self.activation or "identity")(out)
        if mask is not None:
            out = out * mask[:, :, None].astype(out.dtype)
        return out, state
