"""Layer configurations + their functional implementations.

Parity target: reference ``nn/conf/layers/`` (19 config classes, each knowing
how to ``instantiate()`` a runtime impl, report its output type, infer nIn,
and pick a preprocessor — ``nn/conf/layers/Layer.java:130-185``) plus the
runtime impls in ``nn/layers/`` (``BaseLayer.java``, ``ConvolutionLayer.java``,
``BatchNormalization.java``, …).

TPU-native design: config and implementation are unified — each config class
IS the pure-functional layer:

    params          = conf.init_params(key, policy)   # pytree
    state           = conf.init_state(policy)         # e.g. BN running stats
    y, new_state    = conf.apply(params, x, state=..., train=..., rng=...)

Backprop is ``jax.grad`` through ``apply`` — there are no hand-written
``backpropGradient`` methods (reference ``BaseLayer.java:143-167`` has no
analog by design). Dropout is applied to the layer *input* during training,
matching reference ``BaseLayer.preOutput`` → ``Dropout.applyDropout``.

Recurrent layers (GravesLSTM, …) live in ``recurrent.py``; pretrain layers
(AutoEncoder, RBM) in ``pretrain.py``. All register into the same serde
registry here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp

from ... import dtypes as _dtypes
from ...ops import common as _common
from ...ops import convops as _convops
from .. import activations as _activations
from ..weights import Distribution, init_weights
from .inputs import InputType
from .preprocessors import (
    CnnToFeedForwardPreProcessor,
    CnnToRnnPreProcessor,
    FeedForwardToCnnPreProcessor,
    FeedForwardToRnnPreProcessor,
    InputPreProcessor,
    RnnToFeedForwardPreProcessor,
)

# --------------------------------------------------------------------------
# serde registry (polymorphic configs, parity with Jackson subtype registry —
# reference NeuralNetConfiguration.reinitMapperWithSubtypes)
# --------------------------------------------------------------------------

LAYER_REGISTRY: Dict[str, Type["Layer"]] = {}


def register_layer(name: str):
    def deco(cls):
        cls._type_name = name
        LAYER_REGISTRY[name] = cls
        return cls
    return deco


def layer_to_dict(layer: "Layer") -> dict:
    d = {"type": layer._type_name}
    for f in dataclasses.fields(layer):
        v = getattr(layer, f.name)
        if isinstance(v, Distribution):
            v = v.to_dict()
        elif isinstance(v, tuple):
            v = list(v)
        d[f.name] = v
    return d


def layer_from_dict(d: dict) -> "Layer":
    d = dict(d)
    typ = d.pop("type")
    cls = LAYER_REGISTRY[typ]
    field_map = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for k, v in d.items():
        if k not in field_map:
            continue
        if k == "dist" and isinstance(v, dict):
            v = Distribution.from_dict(v)
        elif isinstance(v, list):
            v = tuple(v)
        kwargs[k] = v
    return cls(**kwargs)


# --------------------------------------------------------------------------
# base classes
# --------------------------------------------------------------------------

# Sentinel meaning "inherit from the global builder defaults".
INHERIT = None


@dataclasses.dataclass
class Layer:
    """Base layer config. Fields left as None inherit global builder defaults
    (parity: reference Layer.Builder fields overriding NeuralNetConfiguration
    globals at clone time)."""

    name: Optional[str] = None
    activation: Optional[str] = None          # default "sigmoid" via builder
    weight_init: Optional[str] = None         # default "XAVIER" via builder
    bias_init: Optional[float] = None         # default 0.0
    dist: Optional[Distribution] = None
    dropout: Optional[float] = None           # drop probability (0 disables)
    l1: Optional[float] = None
    l2: Optional[float] = None
    learning_rate: Optional[float] = None     # per-layer LR override
    bias_learning_rate: Optional[float] = None

    _type_name = "base"

    # ---- shape inference hooks (parity Layer.java:130-185) ----
    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        pass

    def preprocessor_for(self, input_type: InputType) -> Optional[InputPreProcessor]:
        return None

    # ---- params ----
    def has_params(self) -> bool:
        return False

    def init_params(self, key, policy=None) -> Dict[str, jax.Array]:
        return {}

    def init_state(self, policy=None) -> Dict[str, jax.Array]:
        return {}

    def param_shapes(self, policy=None) -> Dict[str, Tuple[int, ...]]:
        """Static param shapes (for sharding specs / counting)."""
        return {}

    def regularized_params(self) -> Tuple[str, ...]:
        """Params l1/l2 apply to (parity: Layer.getL1ByParam — weights only)."""
        return ("W",)

    # ---- forward ----
    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        raise NotImplementedError

    # ---- misc ----
    def _act(self, name_override=None):
        return _activations.get(name_override or self.activation or "sigmoid")

    def _dropout_in(self, x, train, rng):
        if train and (self.dropout or 0.0) > 0.0 and rng is not None:
            return _common.apply_dropout(rng, x, float(self.dropout), train)
        return x

    def clone(self, **updates) -> "Layer":
        return dataclasses.replace(self, **updates)


@dataclasses.dataclass
class FeedForwardLayer(Layer):
    """Base for layers with [n_in, n_out] dense weights
    (parity: nn/conf/layers/FeedForwardLayer.java)."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        if self.n_in is None or override:
            self.n_in = input_type.flat_size()

    def preprocessor_for(self, input_type: InputType):
        # parity: InputTypeUtil/FeedForwardLayer.getPreProcessorForInputType
        if input_type.kind == "recurrent":
            return RnnToFeedForwardPreProcessor()
        if input_type.kind == "convolutional":
            return CnnToFeedForwardPreProcessor(
                height=input_type.height, width=input_type.width,
                channels=input_type.channels)
        return None

    def has_params(self) -> bool:
        return True

    def param_shapes(self, policy=None):
        return {"W": (self.n_in, self.n_out), "b": (self.n_out,)}

    def init_params(self, key, policy=None):
        policy = policy or _dtypes.default_policy()
        dt = policy.param_dtype
        w = init_weights(key, (self.n_in, self.n_out),
                         self.weight_init or "XAVIER",
                         fan_in=self.n_in, fan_out=self.n_out,
                         distribution=self.dist, dtype=dt)
        b = jnp.full((self.n_out,), float(self.bias_init or 0.0), dt)
        return {"W": w, "b": b}

    def pre_output(self, params, x, *, policy=None):
        policy = policy or _dtypes.default_policy()
        xc, wc = policy.cast_to_compute(x, params["W"])
        return xc @ wc + params["b"].astype(xc.dtype)

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        x = self._dropout_in(x, train, rng)
        z = self.pre_output(params, x, policy=policy)
        return self._act()(z), state


# --------------------------------------------------------------------------
# concrete feedforward layers
# --------------------------------------------------------------------------


@register_layer("dense")
@dataclasses.dataclass
class DenseLayer(FeedForwardLayer):
    """Fully-connected layer (parity: nn/conf/layers/DenseLayer.java)."""


@dataclasses.dataclass
class BaseOutputLayer(FeedForwardLayer):
    """Output layer with a loss fn (parity: nn/conf/layers/BaseOutputLayer.java,
    runtime nn/layers/BaseOutputLayer.java:92-115 — score via ILossFunction)."""

    loss: str = "negativeloglikelihood"

    def compute_score_array(self, params, x, labels, *, mask=None, policy=None):
        from ... import losses as _losses
        pre = self.pre_output(params, x, policy=policy)
        return _losses.score_array(self.loss, labels, pre,
                                   self.activation or "sigmoid", mask)


@register_layer("output")
@dataclasses.dataclass
class OutputLayer(BaseOutputLayer):
    """Standard 2D output layer (parity: nn/conf/layers/OutputLayer.java)."""


@register_layer("rnn_output")
@dataclasses.dataclass
class RnnOutputLayer(BaseOutputLayer):
    """Time-distributed output for [b,t,f] activations
    (parity: nn/conf/layers/RnnOutputLayer.java)."""

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def preprocessor_for(self, input_type: InputType):
        if input_type.kind == "feedforward":
            return FeedForwardToRnnPreProcessor()
        return None

    def pre_output(self, params, x, *, policy=None):
        # x: [b, t, n_in] — einsum keeps the time axis, one big MXU matmul
        policy = policy or _dtypes.default_policy()
        xc, wc = policy.cast_to_compute(x, params["W"])
        return jnp.einsum("bti,io->bto", xc, wc) + params["b"].astype(xc.dtype)


@register_layer("loss")
@dataclasses.dataclass
class LossLayer(Layer):
    """Parameter-free loss layer (parity: nn/conf/layers/LossLayer.java)."""

    loss: str = "mse"

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        return self._act("identity" if self.activation is None else self.activation)(x), state

    def pre_output(self, params, x, *, policy=None):
        return x

    def compute_score_array(self, params, x, labels, *, mask=None, policy=None):
        from ... import losses as _losses
        return _losses.score_array(self.loss, labels, x,
                                   self.activation or "identity", mask)


@register_layer("activation")
@dataclasses.dataclass
class ActivationLayer(Layer):
    """Activation-only layer (parity: nn/conf/layers/ActivationLayer.java)."""

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        x = self._dropout_in(x, train, rng)
        return self._act()(x), state


@register_layer("dropout")
@dataclasses.dataclass
class DropoutLayer(Layer):
    """Standalone dropout layer."""

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        return self._dropout_in(x, train, rng), state


@register_layer("embedding")
@dataclasses.dataclass
class EmbeddingLayer(FeedForwardLayer):
    """Embedding lookup: int indices [b] or [b,1] -> vectors [b, n_out]
    (parity: nn/conf/layers/EmbeddingLayer.java — W lookup + bias + activation;
    on TPU this lowers to a one-hot matmul or dynamic-gather, both MXU/VMEM
    friendly for the batched case)."""

    has_bias: bool = True

    def param_shapes(self, policy=None):
        shapes = {"W": (self.n_in, self.n_out)}
        if self.has_bias:
            shapes["b"] = (self.n_out,)
        return shapes

    def init_params(self, key, policy=None):
        params = super().init_params(key, policy)
        if not self.has_bias:
            params.pop("b", None)
        return params

    def pre_output(self, params, x, *, policy=None):
        policy = policy or _dtypes.default_policy()
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[:, 0]
        emb = jnp.take(params["W"], idx, axis=0).astype(policy.compute_dtype)
        if self.has_bias:
            emb = emb + params["b"].astype(emb.dtype)
        return emb

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        z = self.pre_output(params, x, policy=policy)
        return self._act("identity" if self.activation is None else self.activation)(z), state


@register_layer("embedding_sequence")
@dataclasses.dataclass
class EmbeddingSequenceLayer(FeedForwardLayer):
    """Token-id sequence embedding: int indices [b, t] (or [b, t, 1]) →
    [b, t, n_out] vectors (parity: nn/conf/layers/EmbeddingSequenceLayer.java).

    The realistic-vocab LM input path: at V ≫ 1k a one-hot [b, t, V] input
    cannot survive host memory, so the network takes raw ids and this
    layer gathers rows of W — on TPU a dynamic-gather, VMEM-friendly and
    free of the one-hot matmul's V-wide FLOPs. ``n_in`` is the VOCAB size
    and must be given explicitly (the [b, t] id input carries no feature
    dim to infer it from). Ids must stay integer-typed end to end — never
    cast through a compute dtype (bf16 rounds ids past 256)."""

    has_bias: bool = False

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        if self.n_in is None:
            raise ValueError(
                "EmbeddingSequenceLayer needs n_in=<vocab size> set "
                "explicitly — the [b, t] id input has no feature dim to "
                "infer it from")

    def preprocessor_for(self, input_type: InputType):
        return None     # ids are consumed raw — never reshaped/cast

    def param_shapes(self, policy=None):
        shapes = {"W": (self.n_in, self.n_out)}
        if self.has_bias:
            shapes["b"] = (self.n_out,)
        return shapes

    def init_params(self, key, policy=None):
        params = super().init_params(key, policy)
        if not self.has_bias:
            params.pop("b", None)
        return params

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        policy = policy or _dtypes.default_policy()
        idx = x.astype(jnp.int32)
        if idx.ndim == 3 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        emb = jnp.take(params["W"], idx, axis=0).astype(policy.compute_dtype)
        if self.has_bias:
            emb = emb + params["b"].astype(emb.dtype)
        out = self._act("identity" if self.activation is None
                        else self.activation)(emb)
        if mask is not None:
            out = out * mask[:, :, None].astype(out.dtype)
        return out, state


# --------------------------------------------------------------------------
# convolutional family
# --------------------------------------------------------------------------


@register_layer("convolution")
@dataclasses.dataclass
class ConvolutionLayer(Layer):
    """2D convolution, NHWC/HWIO (parity: nn/conf/layers/ConvolutionLayer.java;
    runtime nn/layers/convolution/ConvolutionLayer.java + the cuDNN helper —
    here a single XLA conv_general_dilated HLO, MXU-tiled)."""

    n_in: Optional[int] = None      # input channels (inferred)
    n_out: Optional[int] = None     # filters
    kernel_size: Tuple[int, int] = (5, 5)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    border_mode: Optional[str] = None   # None=explicit pad | "same" | "valid"
    groups: int = 1
    has_bias: bool = True           # False for conv+BN pairs (bias is
                                    # redundant before BN's shift)

    def __post_init__(self):
        # ergonomic: padding="same"/"valid" routes to border_mode
        if isinstance(self.padding, str):
            self.border_mode = self.padding
            self.padding = (0, 0)

    def _pad_arg(self):
        if self.border_mode:
            return self.border_mode
        return tuple(self.padding)

    def output_type(self, input_type: InputType) -> InputType:
        h, w = input_type.height, input_type.width
        if self.border_mode == "same":
            oh, ow = -(-h // self.stride[0]), -(-w // self.stride[1])
        else:
            ph, pw = (0, 0) if self.border_mode == "valid" else self.padding
            oh = _convops.conv_output_size(h, self.kernel_size[0], self.stride[0], ph, self.dilation[0])
            ow = _convops.conv_output_size(w, self.kernel_size[1], self.stride[1], pw, self.dilation[1])
        return InputType.convolutional(oh, ow, self.n_out)

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        if self.n_in is None or override:
            self.n_in = input_type.channels

    def preprocessor_for(self, input_type: InputType):
        if input_type.kind == "convolutional_flat":
            return FeedForwardToCnnPreProcessor(
                height=input_type.height, width=input_type.width,
                channels=input_type.channels)
        return None

    def has_params(self) -> bool:
        return True

    def param_shapes(self, policy=None):
        kh, kw = self.kernel_size
        shapes = {"W": (kh, kw, self.n_in // self.groups, self.n_out)}
        if self.has_bias:
            shapes["b"] = (self.n_out,)
        return shapes

    def init_params(self, key, policy=None):
        policy = policy or _dtypes.default_policy()
        dt = policy.param_dtype
        kh, kw = self.kernel_size
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        w = init_weights(key, (kh, kw, self.n_in // self.groups, self.n_out),
                         self.weight_init or "XAVIER", fan_in=fan_in,
                         fan_out=fan_out, distribution=self.dist, dtype=dt)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), float(self.bias_init or 0.0),
                                   dt)
        return params

    def pre_output(self, params, x, *, policy=None):
        policy = policy or _dtypes.default_policy()
        xc, wc = policy.cast_to_compute(x, params["W"])
        z = _convops.conv2d(xc, wc, self.stride, self._pad_arg(), self.dilation,
                            self.groups)
        if self.has_bias:
            z = z + params["b"].astype(z.dtype)
        return z

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        x = self._dropout_in(x, train, rng)
        z = self.pre_output(params, x, policy=policy)
        return self._act()(z), state


@register_layer("subsampling")
@dataclasses.dataclass
class SubsamplingLayer(Layer):
    """Pooling (parity: nn/conf/layers/SubsamplingLayer.java,
    PoolingType MAX/AVG/SUM/PNORM; runtime SubsamplingLayer + cuDNN helper —
    here lax.reduce_window)."""

    pooling_type: str = "max"
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    pnorm: int = 2
    border_mode: Optional[str] = None

    def output_type(self, input_type: InputType) -> InputType:
        h, w = input_type.height, input_type.width
        if self.border_mode == "same":
            oh, ow = -(-h // self.stride[0]), -(-w // self.stride[1])
        else:
            ph, pw = (0, 0) if self.border_mode == "valid" else self.padding
            oh = _convops.conv_output_size(h, self.kernel_size[0], self.stride[0], ph)
            ow = _convops.conv_output_size(w, self.kernel_size[1], self.stride[1], pw)
        return InputType.convolutional(oh, ow, input_type.channels)

    def preprocessor_for(self, input_type: InputType):
        if input_type.kind == "convolutional_flat":
            return FeedForwardToCnnPreProcessor(
                height=input_type.height, width=input_type.width,
                channels=input_type.channels)
        return None

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        pad = self.border_mode if self.border_mode else tuple(self.padding)
        return _convops.pool2d(x, self.pooling_type, self.kernel_size,
                               self.stride, pad, self.pnorm), state


@register_layer("space_to_depth")
@dataclasses.dataclass
class SpaceToDepthLayer(Layer):
    """NHWC space-to-depth: [b, h, w, c] → [b, h/bs, w/bs, bs²·c], channel
    order (di, dj, c) over the bs×bs block.

    Parity: the reference line later ships ``SpaceToDepthLayer``; here it
    doubles as the TPU stem lowering — a 7×7/2 conv on 3 input channels
    (3-deep contracting dim starves the 128-lane MXU) becomes an equivalent
    4×4/1 conv on 12 channels after 2×2 space-to-depth
    (``models.resnet.fold_stem_7x7_to_s2d`` maps the weights exactly).
    """

    block_size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        bs = self.block_size
        if input_type.height % bs or input_type.width % bs:
            raise ValueError(
                f"space_to_depth block {bs} does not divide "
                f"{input_type.height}x{input_type.width}")
        return InputType.convolutional(
            input_type.height // bs, input_type.width // bs,
            input_type.channels * bs * bs)

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        bs = self.block_size
        b, h, w, c = x.shape
        x = x.reshape(b, h // bs, bs, w // bs, bs, c)
        x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
        return x.reshape(b, h // bs, w // bs, bs * bs * c), state


@register_layer("batch_norm")
@dataclasses.dataclass
class BatchNormalization(Layer):
    """Batch normalization over the channel/feature axis.

    Parity: nn/conf/layers/BatchNormalization.java:28-33 (decay=0.9, eps=1e-5,
    gamma=1, beta=0, lockGammaBeta) and runtime
    nn/layers/normalization/BatchNormalization.java (+ cuDNN helper).
    Works on [b,f] and NHWC [b,h,w,c]; stats reduce over all non-channel axes.
    """

    n_out: Optional[int] = None          # feature/channel count (inferred)
    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0
    beta: float = 0.0
    lock_gamma_beta: bool = False

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        if self.n_out is None or override:
            if input_type.kind == "convolutional":
                self.n_out = input_type.channels
            else:
                self.n_out = input_type.flat_size()

    def preprocessor_for(self, input_type: InputType):
        if input_type.kind == "convolutional_flat":
            return FeedForwardToCnnPreProcessor(
                height=input_type.height, width=input_type.width,
                channels=input_type.channels)
        return None

    def has_params(self) -> bool:
        return not self.lock_gamma_beta

    def regularized_params(self) -> Tuple[str, ...]:
        return ()

    def param_shapes(self, policy=None):
        if self.lock_gamma_beta:
            return {}
        return {"gamma": (self.n_out,), "beta": (self.n_out,)}

    def init_params(self, key, policy=None):
        if self.lock_gamma_beta:
            return {}
        policy = policy or _dtypes.default_policy()
        dt = policy.param_dtype
        return {"gamma": jnp.full((self.n_out,), self.gamma, dt),
                "beta": jnp.full((self.n_out,), self.beta, dt)}

    def init_state(self, policy=None):
        policy = policy or _dtypes.default_policy()
        dt = policy.param_dtype
        return {"mean": jnp.zeros((self.n_out,), dt),
                "var": jnp.ones((self.n_out,), dt)}

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        from ...ops import batchnorm as _bn
        if not state:
            state = self.init_state(policy)
        # statistics accumulate in the state dtype (f32 under mixed policy)
        # but the normalize+scale math stays in the activation dtype so
        # bf16 activations don't get promoted to f32 between conv blocks
        stat_dtype = state["mean"].dtype
        if self.lock_gamma_beta:
            g = jnp.full((x.shape[-1],), self.gamma, stat_dtype)
            b = jnp.full((x.shape[-1],), self.beta, stat_dtype)
        else:
            g = params["gamma"].astype(stat_dtype)
            b = params["beta"].astype(stat_dtype)
        if train:
            # fused two-pass BN with a hand-written VJP (ops/batchnorm.py) —
            # the autodiff backward of the naive form costs several extra HBM
            # passes over the activation (the dominant ResNet train cost)
            y, mean, var = _bn.batch_norm_train(x, g, b, self.eps)
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                "var": self.decay * state["var"] + (1 - self.decay) * var,
            }
            return y, new_state
        return _bn.batch_norm_inference(
            x, g, b, state["mean"], state["var"], self.eps), state


@register_layer("layer_norm")
@dataclasses.dataclass
class LayerNormalization(Layer):
    """Layer normalization over the feature (last) axis.

    No reference analog (the reference predates transformers); included as
    the normalization the attention stack needs (``SelfAttentionLayer`` /
    ``models/transformer.py``). Stateless — per-example statistics, no
    running averages — and shape-preserving on [b, f], [b, t, f], NHWC.
    """

    n_out: Optional[int] = None          # feature count (inferred)
    eps: float = 1e-5

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        if self.n_out is None or override:
            if input_type.kind == "convolutional":
                self.n_out = input_type.channels
            else:
                self.n_out = (input_type.size
                              if input_type.kind == "recurrent"
                              else input_type.flat_size())

    def has_params(self) -> bool:
        return True

    def regularized_params(self) -> Tuple[str, ...]:
        return ()

    def param_shapes(self, policy=None):
        return {"gamma": (self.n_out,), "beta": (self.n_out,)}

    def init_params(self, key, policy=None):
        policy = policy or _dtypes.default_policy()
        dt = policy.param_dtype
        return {"gamma": jnp.ones((self.n_out,), dt),
                "beta": jnp.zeros((self.n_out,), dt)}

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        # normalize in at least f32 (bf16 variance over wide features
        # underflows; f64 stays f64 for gradient checking), return in the
        # activation dtype
        cdt = jnp.promote_types(x.dtype, jnp.float32)
        xf = x.astype(cdt)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * params["gamma"].astype(cdt) + params["beta"].astype(cdt)
        return y.astype(x.dtype), state


@register_layer("lrn")
@dataclasses.dataclass
class LocalResponseNormalization(Layer):
    """Cross-channel LRN (parity: nn/conf/layers/LocalResponseNormalization.java
    defaults n=5, k=2, alpha=1e-4, beta=0.75)."""

    n: int = 5
    k: float = 2.0
    alpha: float = 1e-4
    beta: float = 0.75

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        return _convops.lrn(x, self.k, self.n, self.alpha, self.beta), state


@register_layer("global_pooling")
@dataclasses.dataclass
class GlobalPoolingLayer(Layer):
    """Global pooling over spatial or time axes (max/avg/sum/pnorm),
    mask-aware for variable-length sequences."""

    pooling_type: str = "avg"
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "convolutional":
            return InputType.feed_forward(input_type.channels)
        return InputType.feed_forward(input_type.size)

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        if x.ndim == 4:      # NHWC -> [b, c]
            axes = (1, 2)
        elif x.ndim == 3:    # [b, t, f] -> [b, f]
            axes = (1,)
        else:
            return x, state
        kind = self.pooling_type.lower()
        if x.ndim == 3 and mask is not None:
            m = mask[..., None].astype(x.dtype)
            if kind == "avg":
                s = jnp.sum(x * m, axis=axes)
                return s / jnp.maximum(jnp.sum(m, axis=axes), 1.0), state
            if kind == "max":
                neg = jnp.where(m > 0, x, -jnp.inf)
                return jnp.max(neg, axis=axes), state
            if kind == "sum":
                return jnp.sum(x * m, axis=axes), state
        if kind == "avg":
            return jnp.mean(x, axis=axes), state
        if kind == "max":
            return jnp.max(x, axis=axes), state
        if kind == "sum":
            return jnp.sum(x, axis=axes), state
        if kind == "pnorm":
            p = float(self.pnorm)
            if x.ndim == 3 and mask is not None:
                # zero masked timesteps so they don't contribute to the p-norm
                # (parity: reference MaskedReductionUtil PNORM handling)
                x = x * mask[..., None].astype(x.dtype)
            return jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p), state
        raise ValueError(f"unknown pooling type {kind!r}")
