"""Recurrent layer configs: GravesLSTM, GravesBidirectionalLSTM.

Parity: reference ``nn/conf/layers/GravesLSTM.java`` (forgetGateBiasInit
default 1.0, ``:115``), ``GravesBidirectionalLSTM.java``, runtime
``nn/layers/recurrent/LSTMHelpers.java`` (hand-written per-timestep fwd loop
``:146`` / bwd loop ``:287``) and param layout
``nn/params/GravesLSTMParamInitializer.java:85-86`` (W: [nIn, 4nL],
RW: [nL, 4nL+3] — recurrent weights with 3 peephole columns appended).

TPU-native design:
  - the time loop is ``lax.scan`` (compiled once, no per-step dispatch);
    gates for all 4 blocks computed as ONE [.., 4n] matmul per step (MXU);
    the input projection for ALL timesteps is hoisted out of the scan into a
    single batched matmul — the big win over the reference's per-step gemms.
  - backprop-through-time is ``jax.grad`` of the scan (no hand-written BPTT).
  - peepholes are a separate "P" [3, n] param (cleaner pytree than the
    reference's RW-appended columns; same degrees of freedom).
  - gate order in the fused 4n axis: [a (block input), i, f, o].
  - masking: timesteps with mask==0 carry state through unchanged and output 0.

Streaming inference (``rnnTimeStep``, reference MultiLayerNetwork.java:2274)
uses ``step()`` with explicit (h, c) state.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ... import dtypes as _dtypes
from ..weights import init_weights
from .inputs import InputType
from .layers import Layer, register_layer
from .preprocessors import CnnToRnnPreProcessor, FeedForwardToRnnPreProcessor


@dataclasses.dataclass
class BaseRecurrentLayer(Layer):
    """Parity: nn/conf/layers/BaseRecurrentLayer.java."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        if self.n_in is None or override:
            self.n_in = input_type.flat_size()

    def preprocessor_for(self, input_type: InputType):
        if input_type.kind == "feedforward":
            return FeedForwardToRnnPreProcessor()
        if input_type.kind == "convolutional":
            return CnnToRnnPreProcessor(height=input_type.height,
                                        width=input_type.width,
                                        channels=input_type.channels)
        return None

    def has_params(self) -> bool:
        return True


def _lstm_init(key, n_in, n_out, weight_init, dist, forget_bias, dtype):
    k1 = jax.random.fold_in(key, 1)
    k2 = jax.random.fold_in(key, 2)
    fan_in, fan_out = n_in, n_out
    W = init_weights(k1, (n_in, 4 * n_out), weight_init, fan_in=fan_in,
                     fan_out=fan_out, distribution=dist, dtype=dtype)
    RW = init_weights(k2, (n_out, 4 * n_out), weight_init, fan_in=n_out,
                      fan_out=n_out, distribution=dist, dtype=dtype)
    P = jnp.zeros((3, n_out), dtype)
    # bias layout [a,i,f,o]; forget-gate slice initialized to forget_bias
    # (parity: GravesLSTMParamInitializer biasView forget-gate init).
    b = jnp.zeros((4 * n_out,), dtype).at[2 * n_out:3 * n_out].set(forget_bias)
    return {"W": W, "RW": RW, "P": P, "b": b}


def _lstm_scan(params, x, act, gate_act, h0, c0, mask, policy):
    """Run an LSTM over [b, t, n_in] -> [b, t, n_out], returning final state."""
    n = params["RW"].shape[0]
    cdt = policy.compute_dtype
    W = params["W"].astype(cdt)
    RW = params["RW"].astype(cdt)
    P = params["P"].astype(cdt)
    b = params["b"].astype(cdt)
    xb = x.astype(cdt)

    # hoist the input projection out of the scan: [b,t,4n] in one matmul
    zx = jnp.einsum("bti,ij->btj", xb, W) + b

    def step(carry, inp):
        h, c = carry
        zx_t, m_t = inp
        z = zx_t + h @ RW
        a = act(z[:, :n])
        i = gate_act(z[:, n:2 * n] + c * P[0])
        f = gate_act(z[:, 2 * n:3 * n] + c * P[1])
        c_new = f * c + i * a
        o = gate_act(z[:, 3 * n:] + c_new * P[2])
        h_new = o * act(c_new)
        if m_t is not None:
            m = m_t[:, None].astype(h_new.dtype)
            h_new = m * h_new + (1 - m) * h
            c_new = m * c_new + (1 - m) * c
        return (h_new, c_new), h_new

    zx_t = jnp.swapaxes(zx, 0, 1)          # [t, b, 4n]
    m_seq = None if mask is None else jnp.swapaxes(mask, 0, 1)
    if m_seq is None:
        (h, c), hs = lax.scan(lambda cr, z: step(cr, (z, None)), (h0, c0), zx_t)
    else:
        (h, c), hs = lax.scan(step, (h0, c0), (zx_t, m_seq))
    out = jnp.swapaxes(hs, 0, 1)           # [b, t, n]
    if mask is not None:
        out = out * mask[..., None].astype(out.dtype)
    return out, (h, c)


@register_layer("graves_lstm")
@dataclasses.dataclass
class GravesLSTM(BaseRecurrentLayer):
    """LSTM with peepholes (Graves 2013 formulation), lax.scan over time."""

    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"

    def regularized_params(self):
        # l1/l2 apply to input + recurrent weights, not bias/peepholes
        # (parity: GravesLSTM.getL1ByParam — weights only).
        return ("W", "RW")

    def param_shapes(self, policy=None):
        return {"W": (self.n_in, 4 * self.n_out),
                "RW": (self.n_out, 4 * self.n_out),
                "P": (3, self.n_out),
                "b": (4 * self.n_out,)}

    def init_params(self, key, policy=None):
        policy = policy or _dtypes.default_policy()
        return _lstm_init(key, self.n_in, self.n_out,
                          self.weight_init or "XAVIER", self.dist,
                          self.forget_gate_bias_init, policy.param_dtype)

    def _zero_state(self, batch, policy):
        dt = policy.compute_dtype
        return (jnp.zeros((batch, self.n_out), dt),
                jnp.zeros((batch, self.n_out), dt))

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        policy = policy or _dtypes.default_policy()
        x = self._dropout_in(x, train, rng)
        if state and "h" in state:
            h0, c0 = (state["h"].astype(policy.compute_dtype),
                      state["c"].astype(policy.compute_dtype))
        else:
            h0, c0 = self._zero_state(x.shape[0], policy)
        act = self._act("tanh" if self.activation is None else self.activation)
        gact = self._act(self.gate_activation)
        out, (h, c) = _lstm_scan(params, x, act, gact, h0, c0, mask, policy)
        return out, {"h": h, "c": c}

    def step(self, params, x_t, state, *, policy=None):
        """Single timestep for streaming inference (rnnTimeStep parity)."""
        policy = policy or _dtypes.default_policy()
        out, new_state = self.apply(params, x_t[:, None, :], state=state,
                                    policy=policy)
        return out[:, 0, :], new_state


@register_layer("graves_bidirectional_lstm")
@dataclasses.dataclass
class GravesBidirectionalLSTM(BaseRecurrentLayer):
    """Bidirectional Graves LSTM; forward and backward passes are summed
    (parity: nn/layers/recurrent/GravesBidirectionalLSTM.java — activate
    adds fwd + bwd outputs). Params: F (forward) and B (backward) LSTM trees.
    """

    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"

    def param_shapes(self, policy=None):
        base = {"W": (self.n_in, 4 * self.n_out),
                "RW": (self.n_out, 4 * self.n_out),
                "P": (3, self.n_out),
                "b": (4 * self.n_out,)}
        return {f"F_{k}": v for k, v in base.items()} | {
            f"B_{k}": v for k, v in base.items()}

    def init_params(self, key, policy=None):
        policy = policy or _dtypes.default_policy()
        f = _lstm_init(jax.random.fold_in(key, 0), self.n_in, self.n_out,
                       self.weight_init or "XAVIER", self.dist,
                       self.forget_gate_bias_init, policy.param_dtype)
        b = _lstm_init(jax.random.fold_in(key, 1), self.n_in, self.n_out,
                       self.weight_init or "XAVIER", self.dist,
                       self.forget_gate_bias_init, policy.param_dtype)
        return {f"F_{k}": v for k, v in f.items()} | {
            f"B_{k}": v for k, v in b.items()}

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        policy = policy or _dtypes.default_policy()
        x = self._dropout_in(x, train, rng)
        act = self._act("tanh" if self.activation is None else self.activation)
        gact = self._act(self.gate_activation)
        bsz = x.shape[0]
        dt = policy.compute_dtype
        zeros = (jnp.zeros((bsz, self.n_out), dt), jnp.zeros((bsz, self.n_out), dt))
        fp = {k[2:]: v for k, v in params.items() if k.startswith("F_")}
        bp = {k[2:]: v for k, v in params.items() if k.startswith("B_")}
        out_f, _ = _lstm_scan(fp, x, act, gact, *zeros, mask, policy)
        x_rev = jnp.flip(x, axis=1)
        mask_rev = None if mask is None else jnp.flip(mask, axis=1)
        out_b, _ = _lstm_scan(bp, x_rev, act, gact, *zeros, mask_rev, policy)
        out = out_f + jnp.flip(out_b, axis=1)
        return out, state

    def regularized_params(self):
        return ("F_W", "F_RW", "B_W", "B_RW")


@register_layer("last_time_step")
@dataclasses.dataclass
class LastTimeStepLayer(Layer):
    """[b, t, f] → [b, f] at the last unmasked step (parity: the reference's
    ``LastTimeStepVertex`` as a sequential layer; used by Keras import for
    ``return_sequences=False`` recurrent layers)."""

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.size)

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        if mask is None:
            return x[:, -1, :], state
        t = x.shape[1]
        idx = t - 1 - jnp.argmax(jnp.flip(mask > 0, axis=1), axis=1)
        return x[jnp.arange(x.shape[0]), idx], state


@register_layer("time_distributed_dense")
@dataclasses.dataclass
class TimeDistributedDenseLayer(BaseRecurrentLayer):
    """Dense applied independently at every timestep: [b, t, n_in] →
    [b, t, n_out] (parity: the reference's Keras ``TimeDistributedDense``
    import, ``modelimport/keras/LayerConfiguration.java:43``, which it
    realizes as a DenseLayer in an RnnToFeedForward/FeedForwardToRnn
    sandwich). TPU-native: no reshape sandwich — one batched einsum keeps
    the time axis so XLA sees a single [b*t, n_in]×[n_in, n_out] MXU
    matmul without layout round-trips. Inherits BaseRecurrentLayer's
    input handling (FeedForwardToRnn / CnnToRnn preprocessors)."""

    def param_shapes(self, policy=None):
        return {"W": (self.n_in, self.n_out), "b": (self.n_out,)}

    def init_params(self, key, policy=None):
        policy = policy or _dtypes.default_policy()
        dt = policy.param_dtype
        w = init_weights(key, (self.n_in, self.n_out),
                         self.weight_init or "XAVIER",
                         fan_in=self.n_in, fan_out=self.n_out,
                         distribution=self.dist, dtype=dt)
        b = jnp.full((self.n_out,), float(self.bias_init or 0.0), dt)
        return {"W": w, "b": b}

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        policy = policy or _dtypes.default_policy()
        x = self._dropout_in(x, train, rng)
        xc, wc = policy.cast_to_compute(x, params["W"])
        z = jnp.einsum("bti,io->bto", xc, wc) + params["b"].astype(xc.dtype)
        return self._act()(z), state
