"""ComputationGraph configuration: DAG of named vertices.

Parity: reference ``nn/conf/ComputationGraphConfiguration.java``
(``GraphBuilder.addLayer/addVertex/addInputs/setOutputs``), graph vertex
configs in ``nn/conf/graph/`` (``MergeVertex``, ``ElementWiseVertex``,
``SubsetVertex``, ``StackVertex``, ``UnstackVertex``, ``L2Vertex``,
``ScaleVertex``, ``PreprocessorVertex``, ``rnn/LastTimeStepVertex``,
``rnn/DuplicateToTimeSeriesVertex``) and the topological sort at
``nn/graph/ComputationGraph.java:810``.

TPU-native design: vertices are pure functions over activations; the runtime
(``nn/graph_runtime.py``) traces the whole topo-ordered DAG into ONE jitted
program, so "vertex dispatch" has zero runtime cost — XLA fuses across vertex
boundaries. Mask propagation follows the activations (each vertex maps input
masks to an output mask).
"""

from __future__ import annotations

import copy
import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

import jax
import jax.numpy as jnp

from .inputs import InputType
from .layers import Layer, layer_from_dict, layer_to_dict
from .preprocessors import (InputPreProcessor, call_preprocessor,
                            preprocessor_from_dict)
from .training import TrainingConfig

# ensure recurrent layer types are registered for serde
from . import recurrent as _recurrent  # noqa: F401

# --------------------------------------------------------------------------
# vertex registry (polymorphic serde, same pattern as layers)
# --------------------------------------------------------------------------

VERTEX_REGISTRY: Dict[str, Type["GraphVertex"]] = {}


def register_vertex(name: str):
    def deco(cls):
        cls._type_name = name
        VERTEX_REGISTRY[name] = cls
        return cls
    return deco


def vertex_to_dict(v: "GraphVertex") -> dict:
    d = {"type": v._type_name}
    for f in dataclasses.fields(v):
        val = getattr(v, f.name)
        if isinstance(val, Layer):
            val = {"__layer__": layer_to_dict(val)}
        elif isinstance(val, InputPreProcessor):
            val = {"__preprocessor__": val.to_dict()}
        elif isinstance(val, tuple):
            val = list(val)
        d[f.name] = val
    return d


def vertex_from_dict(d: dict) -> "GraphVertex":
    d = dict(d)
    typ = d.pop("type")
    cls = VERTEX_REGISTRY[typ]
    field_map = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for k, v in d.items():
        if k not in field_map:
            continue
        if isinstance(v, dict) and "__layer__" in v:
            v = layer_from_dict(v["__layer__"])
        elif isinstance(v, dict) and "__preprocessor__" in v:
            v = preprocessor_from_dict(v["__preprocessor__"])
        elif isinstance(v, list):
            v = tuple(v)
        kwargs[k] = v
    return cls(**kwargs)


# --------------------------------------------------------------------------
# vertex base + impls
# --------------------------------------------------------------------------


@dataclasses.dataclass
class GraphVertex:
    """A pure function over one or more input activations."""

    _type_name = "base"

    # ---- params (layer vertices override) ----
    def has_params(self) -> bool:
        return False

    def init_params(self, key, policy=None) -> Dict[str, Any]:
        return {}

    def init_state(self, policy=None) -> Dict[str, Any]:
        return {}

    def param_shapes(self, policy=None) -> Dict[str, Tuple[int, ...]]:
        return {}

    # ---- shape inference ----
    def output_type(self, input_types: List[InputType]) -> InputType:
        raise NotImplementedError

    def set_n_in(self, input_types: List[InputType], override: bool = False) -> None:
        pass

    # ---- forward: (params, [x...], state, train, rng, [mask...]) ----
    def apply(self, params, xs: List[jax.Array], *, state=None, train=False,
              rng=None, masks=None, policy=None, minibatch=None):
        raise NotImplementedError

    def output_mask(self, masks: Optional[List[Optional[jax.Array]]],
                    minibatch: Optional[int] = None):
        """Propagate masks (default: first non-None input mask).
        `minibatch` is the batch size of this vertex's input activations,
        for mask-reshaping vertices."""
        if not masks:
            return None
        for m in masks:
            if m is not None:
                return m
        return None

    def output_minibatch(self, in_mbs: List[int]) -> int:
        """The EXAMPLE count of this vertex's output. Time-flattened
        activations make shape[0] = b·t, so the runtime tracks the true
        example count along the DAG; batch-axis vertices (Stack/Unstack)
        override."""
        return in_mbs[0]


@register_vertex("layer")
@dataclasses.dataclass
class LayerVertex(GraphVertex):
    """Wraps a Layer config (+ optional preprocessor) as a single-input vertex
    (parity: ``nn/graph/vertex/impl/LayerVertex.java``)."""

    layer: Layer = None
    preprocessor: Optional[InputPreProcessor] = None

    def has_params(self) -> bool:
        return self.layer.has_params()

    def init_params(self, key, policy=None):
        return self.layer.init_params(key, policy)

    def init_state(self, policy=None):
        return self.layer.init_state(policy)

    def param_shapes(self, policy=None):
        return self.layer.param_shapes(policy)

    def output_type(self, input_types):
        it = input_types[0]
        if self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        return self.layer.output_type(it)

    def set_n_in(self, input_types, override=False):
        it = input_types[0]
        if self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        self.layer.set_n_in(it, override)

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        x = xs[0]
        mask = masks[0] if masks else None
        if self.preprocessor is not None:
            # the NETWORK minibatch, not x.shape[0]: time-flattened inputs
            # arrive as [b*t, f] and FeedForwardToRnn must rebuild [b, t, f]
            mb = minibatch if minibatch is not None else x.shape[0]
            x = call_preprocessor(self.preprocessor, x, minibatch_size=mb,
                                  rng=rng)
            mask = self.preprocessor.transform_mask(mask, minibatch_size=mb)
        return self.layer.apply(params, x, state=state, train=train, rng=rng,
                                mask=mask, policy=policy)


@register_vertex("merge")
@dataclasses.dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature (last) axis
    (parity: ``nn/conf/graph/MergeVertex.java`` — NHWC makes depth concat the
    last axis for CNN activations too)."""

    def output_type(self, input_types):
        first = input_types[0]
        if first.kind == "convolutional":
            return InputType.convolutional(
                first.height, first.width,
                sum(t.channels for t in input_types))
        if first.kind == "recurrent":
            return InputType.recurrent(sum(t.size for t in input_types),
                                       first.timesteps)
        return InputType.feed_forward(sum(t.flat_size() for t in input_types))

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        return jnp.concatenate(xs, axis=-1), state


@register_vertex("elementwise")
@dataclasses.dataclass
class ElementWiseVertex(GraphVertex):
    """Pointwise add/subtract/product/average/max over equal-shaped inputs
    (parity: ``nn/conf/graph/ElementWiseVertex.java``; the residual-sum
    building block of ResNet)."""

    op: str = "add"   # add | subtract | product | average | max

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        op = self.op.lower()
        if op == "add":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
        elif op == "subtract":
            if len(xs) != 2:
                raise ValueError("subtract needs exactly 2 inputs")
            out = xs[0] - xs[1]
        elif op == "product":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
        elif op == "average":
            out = sum(xs) / float(len(xs))
        elif op == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
        else:
            raise ValueError(f"unknown elementwise op {self.op!r}")
        return out, state


@register_vertex("subset")
@dataclasses.dataclass
class SubsetVertex(GraphVertex):
    """Feature range [from_idx, to_idx] inclusive (parity:
    ``nn/conf/graph/SubsetVertex.java``)."""

    from_idx: int = 0
    to_idx: int = 0

    def output_type(self, input_types):
        n = self.to_idx - self.from_idx + 1
        it = input_types[0]
        if it.kind == "recurrent":
            return InputType.recurrent(n, it.timesteps)
        if it.kind == "convolutional":   # subset over channels (last axis)
            return InputType.convolutional(it.height, it.width, n)
        return InputType.feed_forward(n)

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        return xs[0][..., self.from_idx:self.to_idx + 1], state


@register_vertex("stack")
@dataclasses.dataclass
class StackVertex(GraphVertex):
    """Stack inputs along the batch axis (parity:
    ``nn/conf/graph/StackVertex.java`` — used for weight-shared towers)."""

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        return jnp.concatenate(xs, axis=0), state

    def output_mask(self, masks, minibatch=None):
        if not masks or all(m is None for m in masks):
            return None
        if any(m is None for m in masks):
            raise ValueError("StackVertex: either all or no inputs may be masked")
        return jnp.concatenate(masks, axis=0)

    def output_minibatch(self, in_mbs):
        return sum(in_mbs)


@register_vertex("unstack")
@dataclasses.dataclass
class UnstackVertex(GraphVertex):
    """Take batch slice `from_idx` of `stack_size` equal slices (parity:
    ``nn/conf/graph/UnstackVertex.java``)."""

    from_idx: int = 0
    stack_size: int = 1

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        x = xs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_idx * step:(self.from_idx + 1) * step], state

    def output_mask(self, masks, minibatch=None):
        m = super().output_mask(masks)
        if m is None:
            return None
        step = m.shape[0] // self.stack_size
        return m[self.from_idx * step:(self.from_idx + 1) * step]

    def output_minibatch(self, in_mbs):
        return in_mbs[0] // self.stack_size


@register_vertex("scale")
@dataclasses.dataclass
class ScaleVertex(GraphVertex):
    """Multiply by a fixed scalar (parity: ``nn/conf/graph/ScaleVertex.java``)."""

    scale: float = 1.0

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        return xs[0] * self.scale, state


@register_vertex("shift")
@dataclasses.dataclass
class ShiftVertex(GraphVertex):
    """Add a fixed scalar (parity: ``nn/conf/graph/ShiftVertex.java``)."""

    shift: float = 0.0

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        return xs[0] + self.shift, state


@register_vertex("l2")
@dataclasses.dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs → [b, 1] (parity:
    ``nn/conf/graph/L2Vertex.java``; used by siamese/triplet nets)."""

    epsilon: float = 1e-8

    def output_type(self, input_types):
        return InputType.feed_forward(1)

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        a = xs[0].reshape(xs[0].shape[0], -1)
        b = xs[1].reshape(xs[1].shape[0], -1)
        d2 = jnp.sum(jnp.square(a - b), axis=1, keepdims=True)
        return jnp.sqrt(d2 + self.epsilon), state


@register_vertex("l2normalize")
@dataclasses.dataclass
class L2NormalizeVertex(GraphVertex):
    """x / ||x||_2 over the feature axes (parity:
    ``nn/conf/graph/L2NormalizeVertex.java``)."""

    epsilon: float = 1e-8

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        x = xs[0]
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True)
                        + self.epsilon)
        return x / norm, state


@register_vertex("preprocessor")
@dataclasses.dataclass
class PreprocessorVertex(GraphVertex):
    """Standalone shape-adapter vertex (parity:
    ``nn/conf/graph/PreprocessorVertex.java``)."""

    preprocessor: InputPreProcessor = None

    def output_type(self, input_types):
        return self.preprocessor.output_type(input_types[0])

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        x = xs[0]
        mb = minibatch if minibatch is not None else x.shape[0]
        return call_preprocessor(self.preprocessor, x, minibatch_size=mb,
                                 rng=rng), state

    def output_mask(self, masks, minibatch: Optional[int] = None):
        m = masks[0] if masks else None
        if m is None:
            return None
        return self.preprocessor.transform_mask(m, minibatch_size=minibatch)


@register_vertex("last_time_step")
@dataclasses.dataclass
class LastTimeStepVertex(GraphVertex):
    """[b, t, f] → [b, f] at the last unmasked step (parity:
    ``nn/conf/graph/rnn/LastTimeStepVertex.java``)."""

    def output_type(self, input_types):
        return InputType.feed_forward(input_types[0].size)

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        x = xs[0]
        mask = masks[0] if masks else None
        if mask is None:
            return x[:, -1, :], state
        # index of last step with mask > 0, per example
        t = x.shape[1]
        idx = t - 1 - jnp.argmax(jnp.flip(mask > 0, axis=1), axis=1)
        return x[jnp.arange(x.shape[0]), idx], state

    def output_mask(self, masks, minibatch=None):
        return None  # output is per-example, fully active


@register_vertex("duplicate_to_time_series")
@dataclasses.dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[b, f] broadcast to [b, t, f]; t taken from a reference input by name
    (parity: ``nn/conf/graph/rnn/DuplicateToTimeSeriesVertex.java``). The
    runtime passes the reference activation as second input."""

    reference_input: str = ""

    def output_type(self, input_types):
        ref = input_types[1] if len(input_types) > 1 else None
        return InputType.recurrent(input_types[0].flat_size(),
                                   ref.timesteps if ref else None)

    def apply(self, params, xs, *, state=None, train=False, rng=None,
              masks=None, policy=None, minibatch=None):
        x, ref = xs[0], xs[1]
        t = ref.shape[1]
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], t, x.shape[1])), state

    def output_mask(self, masks, minibatch=None):
        return masks[1] if masks and len(masks) > 1 else None


# --------------------------------------------------------------------------
# configuration + builder
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ComputationGraphConfiguration:
    """Named DAG: vertices, their input edges, network inputs/outputs.

    Parity: ``nn/conf/ComputationGraphConfiguration.java``.
    """

    vertices: Dict[str, GraphVertex]
    vertex_inputs: Dict[str, List[str]]
    network_inputs: List[str]
    network_outputs: List[str]
    training: TrainingConfig = dataclasses.field(default_factory=TrainingConfig)
    input_types: Optional[List[InputType]] = None
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20

    # ---- topology ----
    def topological_order(self) -> List[str]:
        """Kahn topo sort, deterministic (insertion order among ready nodes).
        Parity: ``ComputationGraph.java:810``."""
        indeg = {name: 0 for name in self.vertices}
        children: Dict[str, List[str]] = {name: [] for name in self.vertices}
        for name, inputs in self.vertex_inputs.items():
            for inp in inputs:
                if inp in self.vertices:
                    indeg[name] += 1
                    children[inp].append(name)
                elif inp not in self.network_inputs:
                    raise ValueError(
                        f"vertex {name!r} references unknown input {inp!r}")
        ready = [n for n in self.vertices if indeg[n] == 0]
        order: List[str] = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            for c in children[n]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
        if len(order) != len(self.vertices):
            cyc = sorted(set(self.vertices) - set(order))
            raise ValueError(f"graph has a cycle involving {cyc}")
        return order

    def validate(self) -> None:
        for out in self.network_outputs:
            if out not in self.vertices:
                raise ValueError(f"network output {out!r} is not a vertex")
        for name in self.vertices:
            if name in self.network_inputs:
                raise ValueError(f"{name!r} is both a vertex and a network input")
            if not self.vertex_inputs.get(name):
                raise ValueError(f"vertex {name!r} has no inputs")
        self.topological_order()

    # ---- shape inference over the DAG ----
    def infer_shapes(self) -> Dict[str, InputType]:
        if self.input_types is None:
            return {}
        types: Dict[str, InputType] = dict(
            zip(self.network_inputs, self.input_types))
        for name in self.topological_order():
            v = self.vertices[name]
            in_types = [types[i] for i in self.vertex_inputs[name]]
            v.set_n_in(in_types, override=False)
            types[name] = v.output_type(in_types)
        return types

    # ---- serde ----
    def to_dict(self) -> dict:
        return {
            "format_version": 1,
            "framework": "deeplearning4j_tpu",
            "model": "computation_graph",
            "vertices": {n: vertex_to_dict(v) for n, v in self.vertices.items()},
            "vertex_inputs": self.vertex_inputs,
            "network_inputs": self.network_inputs,
            "network_outputs": self.network_outputs,
            "training": self.training.to_dict(),
            "input_types": ([t.to_dict() for t in self.input_types]
                            if self.input_types else None),
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
        }

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration(
            vertices={n: vertex_from_dict(v)
                      for n, v in d["vertices"].items()},
            vertex_inputs={n: list(v) for n, v in d["vertex_inputs"].items()},
            network_inputs=list(d["network_inputs"]),
            network_outputs=list(d["network_outputs"]),
            training=TrainingConfig.from_dict(d.get("training", {})),
            input_types=([InputType.from_dict(t) for t in d["input_types"]]
                         if d.get("input_types") else None),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
        )

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        """(parity: the reference's ``toYaml`` Jackson mapper)"""
        import yaml
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        import yaml
        return ComputationGraphConfiguration.from_dict(yaml.safe_load(s))


class GraphBuilder:
    """Fluent DAG builder (parity: ``ComputationGraphConfiguration.GraphBuilder``
    reached via ``NeuralNetConfiguration.Builder.graphBuilder()`` ``:613``).

    Usage::

        conf = (NeuralNetConfiguration.builder().updater("adam")
                .graph_builder()
                .add_inputs("in")
                .add_layer("conv1", ConvolutionLayer(...), "in")
                .add_vertex("res", ElementWiseVertex(op="add"), "conv1", "in")
                .add_layer("out", OutputLayer(...), "res")
                .set_outputs("out")
                .set_input_types(InputType.convolutional(32, 32, 3))
                .build())
    """

    def __init__(self, base):
        self._base = base
        self._vertices: Dict[str, GraphVertex] = {}
        self._vertex_inputs: Dict[str, List[str]] = {}
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._input_types: Optional[List[InputType]] = None
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str,
                  preprocessor: Optional[InputPreProcessor] = None) -> "GraphBuilder":
        layer = self._base._apply_defaults(layer)
        return self.add_vertex(
            name, LayerVertex(layer=layer, preprocessor=preprocessor), *inputs)

    def add_vertex(self, name: str, vertex: GraphVertex,
                   *inputs: str) -> "GraphBuilder":
        if name in self._vertices or name in self._inputs:
            raise ValueError(f"duplicate vertex name {name!r}")
        if not inputs:
            raise ValueError(f"vertex {name!r} needs at least one input")
        self._vertices[name] = vertex
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    def backprop_type(self, kind: str) -> "GraphBuilder":
        self._backprop_type = kind.lower()
        return self

    def t_bptt_forward_length(self, n: int) -> "GraphBuilder":
        self._tbptt_fwd = int(n)
        return self

    def t_bptt_backward_length(self, n: int) -> "GraphBuilder":
        self._tbptt_back = int(n)
        return self

    def build(self) -> ComputationGraphConfiguration:
        conf = ComputationGraphConfiguration(
            vertices=self._vertices,
            vertex_inputs=self._vertex_inputs,
            network_inputs=list(self._inputs),
            network_outputs=list(self._outputs),
            training=copy.deepcopy(self._base._t),
            input_types=self._input_types,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
        )
        conf.validate()
        # auto-insert preprocessors + infer nIn along the DAG
        if conf.input_types is not None:
            types: Dict[str, InputType] = dict(
                zip(conf.network_inputs, conf.input_types))
            for name in conf.topological_order():
                v = conf.vertices[name]
                in_types = [types[i] for i in conf.vertex_inputs[name]]
                if isinstance(v, LayerVertex) and v.preprocessor is None:
                    v.preprocessor = v.layer.preprocessor_for(in_types[0])
                v.set_n_in(in_types, override=False)
                types[name] = v.output_type(in_types)
        return conf
