"""Mixture-of-experts FFN as a config-DSL layer.

No reference analog (SURVEY §2.9: EP = NO) — the expert-parallelism
north-star surfaced in the same builder DSL as every other layer, so MoE
transformers are ordinary ComputationGraphs (serde, listeners, remat,
SP/PP trainers all apply). The math is ``parallel/expert.py``'s
dense-dispatch formulation (every expert computes every token, top-k
gates zero the rest — static shapes, no scatter, compiler-friendly) with
the time axis preserved, so under a mesh the expert-stacked einsums
partition over ``ep`` (see ``parallel.expert.expert_param_specs`` /
``ExpertParallelGraphTrainer``) and
the time axis can simultaneously shard over ``seq``.

The Shazeer-style load-balancing auxiliary loss is returned through the
layer's state under ``"aux_loss"`` — both network runtimes add any such
entries to the training objective (scaled by ``aux_weight`` here, so the
trainer just sums).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ... import dtypes as _dtypes
from .inputs import InputType
from .layers import Layer, register_layer
from ..weights import init_weights


@register_layer("moe")
@dataclasses.dataclass
class MoELayer(Layer):
    """Top-k routed mixture-of-experts FFN: [b, t, f] → [b, t, f] (or
    [b, f] → [b, f]).

    Params: ``router`` [n_in, E], expert-stacked ``w1`` [E, n_in,
    d_hidden], ``b1`` [E, d_hidden], ``w2`` [E, d_hidden, n_out], ``b2``
    [E, n_out] — the leading E dim is what expert parallelism shards.
    """

    n_in: Optional[int] = None
    n_out: Optional[int] = None          # defaults to n_in
    d_hidden: int = 256
    n_experts: int = 8
    top_k: int = 2
    aux_weight: float = 0.01

    def output_type(self, input_type: InputType) -> InputType:
        n = self.n_out or self.n_in
        if input_type.kind == "recurrent":
            return InputType.recurrent(n, input_type.timesteps)
        return InputType.feed_forward(n)

    def set_n_in(self, input_type: InputType, override: bool = False) -> None:
        if self.n_in is None or override:
            self.n_in = input_type.flat_size()
        if self.n_out is None:
            self.n_out = self.n_in
        if self.top_k > self.n_experts:
            raise ValueError(f"top_k={self.top_k} > "
                             f"n_experts={self.n_experts}")

    def has_params(self) -> bool:
        return True

    def param_shapes(self, policy=None) -> Dict[str, Tuple[int, ...]]:
        e, h = self.n_experts, self.d_hidden
        return {"router": (self.n_in, e),
                "w1": (e, self.n_in, h), "b1": (e, h),
                "w2": (e, h, self.n_out), "b2": (e, self.n_out)}

    def regularized_params(self) -> Tuple[str, ...]:
        return ("w1", "w2")

    def init_params(self, key, policy=None):
        policy = policy or _dtypes.default_policy()
        dt = policy.param_dtype
        e, h = self.n_experts, self.d_hidden
        kr, k1, k2 = jax.random.split(key, 3)
        wi = self.weight_init or "XAVIER"

        def stack(k, shape, fan_in, fan_out):
            ks = jax.random.split(k, e)
            return jnp.stack([
                init_weights(ks[i], shape, wi, fan_in=fan_in,
                             fan_out=fan_out, distribution=self.dist,
                             dtype=dt) for i in range(e)])

        return {
            "router": init_weights(kr, (self.n_in, e), wi,
                                   fan_in=self.n_in, fan_out=e, dtype=dt),
            "w1": stack(k1, (self.n_in, h), self.n_in, h),
            "b1": jnp.zeros((e, h), dt),
            "w2": stack(k2, (h, self.n_out), h, self.n_out),
            "b2": jnp.zeros((e, self.n_out), dt),
        }

    def apply(self, params, x, *, state=None, train=False, rng=None,
              mask=None, policy=None):
        policy = policy or _dtypes.default_policy()
        x = self._dropout_in(x, train, rng)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]                       # [b, 1, f]
        xc, router = policy.cast_to_compute(x, params["router"])
        e = self.n_experts
        logits = jnp.einsum("btd,de->bte", xc, router)
        # routing numerics at >= f32 (and f64 under an x64 policy, so the
        # gradient-check suite sees the true derivative)
        gate_dt = jnp.promote_types(logits.dtype, jnp.float32)
        gates = jax.nn.softmax(logits.astype(gate_dt), axis=-1)
        if self.top_k < e:
            # lax.top_k breaks ties deterministically (lowest index), so
            # EXACTLY top_k experts fire even for uniform gates
            _, idx = jax.lax.top_k(gates, self.top_k)       # [b, t, k]
            keep = jax.nn.one_hot(idx, e).sum(axis=2) > 0   # [b, t, E]
            masked = jnp.where(keep, gates, 0.0)
            weights = masked / jnp.maximum(
                masked.sum(-1, keepdims=True), 1e-9)
        else:
            keep = jnp.ones_like(gates, bool)
            weights = gates
        w1 = params["w1"].astype(xc.dtype)
        w2 = params["w2"].astype(xc.dtype)
        # dense dispatch, time axis preserved: [E, b, t, h] hidden
        h = jax.nn.relu(jnp.einsum("btd,edh->ebth", xc, w1)
                        + params["b1"].astype(xc.dtype)[:, None, None, :])
        y_e = (jnp.einsum("ebth,ehd->ebtd", h, w2)
               + params["b2"].astype(xc.dtype)[:, None, None, :])
        y = jnp.einsum("bte,ebtd->btd", weights.astype(xc.dtype), y_e)
        # Shazeer-style load-balancing aux: E * sum_e mean_gate * mean_keep
        if mask is not None:
            m = mask.astype(gate_dt)[:, :, None]
            denom = jnp.maximum(jnp.sum(m), 1.0)
            gate_frac = jnp.sum(gates * m, axis=(0, 1)) / denom
            keep_frac = jnp.sum(keep.astype(gate_dt) * m,
                                axis=(0, 1)) / denom
            y = y * m.astype(y.dtype)
        else:
            gate_frac = jnp.mean(gates, axis=(0, 1))
            keep_frac = jnp.mean(keep.astype(gate_dt), axis=(0, 1))
        aux = e * jnp.sum(gate_frac * keep_frac)
        if squeeze:
            y = y[:, 0, :]
        out_state = dict(state or {})
        out_state["aux_loss"] = self.aux_weight * aux
        return y, out_state
