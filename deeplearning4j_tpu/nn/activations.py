"""Activation functions.

String-keyed registry matching the reference's activation-function strings
(reference ``nn/conf/NeuralNetConfiguration.java:480`` — default "sigmoid";
ND4J op factory names: sigmoid, tanh, relu, leakyrelu, softmax, identity,
softplus, softsign, hardtanh, hardsigmoid, elu, cube, rationaltanh).

All are pure jnp functions; derivatives come from JAX autodiff (the reference
hand-codes derivative ops — ``nn/layers/BaseLayer.java:147``).
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

Activation = Callable[[jax.Array], jax.Array]

_REGISTRY: Dict[str, Activation] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name.lower()] = fn
        return fn
    return deco


def get(name: str) -> Activation:
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown activation {name!r}; known: {sorted(_REGISTRY)}") from None


def names():
    return sorted(_REGISTRY)


@register("identity")
@register("linear")
def identity(x):
    return x


@register("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register("tanh")
def tanh(x):
    return jnp.tanh(x)


@register("relu")
def relu(x):
    return jax.nn.relu(x)


@register("leakyrelu")
def leakyrelu(x, alpha: float = 0.01):
    return jnp.where(x >= 0, x, alpha * x)


@register("softmax")
def softmax(x):
    return jax.nn.softmax(x, axis=-1)


@register("softplus")
def softplus(x):
    return jax.nn.softplus(x)


@register("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@register("hardtanh")
def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


@register("hardsigmoid")
def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


@register("elu")
def elu(x):
    return jax.nn.elu(x)


@register("selu")
def selu(x):
    return jax.nn.selu(x)


@register("gelu")
def gelu(x):
    return jax.nn.gelu(x)


@register("swish")
@register("silu")
def swish(x):
    return jax.nn.silu(x)


@register("cube")
def cube(x):
    return x ** 3


@register("rationaltanh")
def rationaltanh(x):
    # 1.7159 * tanh_approx(2x/3), tanh_approx(y) = sign(y)(1 - 1/(1+|y|+y^2+1.41645 y^4))
    # — ND4J RationalTanh op semantics.
    y = 2.0 * x / 3.0
    a = jnp.abs(y)
    approx = jnp.sign(y) * (1.0 - 1.0 / (1.0 + a + y * y + 1.41645 * a ** 4))
    return 1.7159 * approx
