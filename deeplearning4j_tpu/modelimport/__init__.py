"""Keras model import.

Parity: reference ``deeplearning4j-modelimport`` —
``keras/Model.java:58`` (``importSequentialModel``), ``:78``
(``importFunctionalApiModel``), ``ModelConfiguration.java`` (config JSON →
network configuration), ``LayerConfiguration.java:42-47`` (supported layers:
Dense, TimeDistributedDense, LSTM, Convolution2D, MaxPooling2D, Flatten,
Dropout, Activation + the activation-name mapping).

TPU-native: HDF5 read via h5py (replacing JavaCPP hdf5 bindings); weights are
transposed into this framework's conventions (NHWC/HWIO convs; [in, out]
dense kernels — Keras already stores those layouts, the reference had to
transpose into its own NCHW/F-order world, we mostly do NOT).
"""

from .keras import KerasModelImport

__all__ = ["KerasModelImport"]
