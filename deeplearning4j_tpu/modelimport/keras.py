"""Keras HDF5 importer.

Parity: reference ``keras/Model.java`` / ``ModelConfiguration.java`` /
``LayerConfiguration.java``. Reads a Keras-saved ``.h5`` archive: the
``model_config`` JSON attribute picks the architecture, ``model_weights``
holds per-layer arrays. Supports Keras 1.x and 2.x sequential configs and
linear/residual functional graphs.

Supported layers (superset of the reference's ``LayerConfiguration.java:42``):
Dense, Activation, Dropout, Flatten, Convolution2D/Conv2D, MaxPooling2D,
AveragePooling2D, LSTM, Embedding, BatchNormalization.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..nn.conf.builders import NeuralNetConfiguration
from ..nn.conf.inputs import InputType
from ..nn.conf.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    DropoutLayer, EmbeddingLayer, OutputLayer, RnnOutputLayer,
    SubsamplingLayer)
from ..nn.conf.recurrent import (
    GravesLSTM, LastTimeStepLayer, TimeDistributedDenseLayer)

_ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "softmax": "softmax",
    "sigmoid": "sigmoid", "tanh": "tanh", "hard_sigmoid": "hardsigmoid",
    "softplus": "softplus", "elu": "elu", "selu": "selu",
    "softsign": "softsign", "leaky_relu": "leakyrelu",
}


def _map_activation(name: Optional[str]) -> str:
    if not name:
        return "identity"
    return _ACTIVATIONS.get(name, name)


class KerasModelImport:
    """Static import entry points (parity: ``Model.importSequentialModel``)."""

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    @staticmethod
    def import_sequential_model(path: str, *, train: bool = False,
                                loss: str = "mcxent"):
        """h5 → initialized MultiLayerNetwork with imported weights.

        The final Dense layer becomes an OutputLayer with `loss` so the
        returned net is trainable/evaluable (reference enforceTrainingConfig
        analog)."""
        import h5py
        from ..nn.multilayer import MultiLayerNetwork

        with h5py.File(path, "r") as f:
            model_config = KerasModelImport._read_model_config(f)
            class_name = model_config["class_name"]
            if class_name != "Sequential":
                raise ValueError(
                    f"not a sequential model ({class_name}); use "
                    "import_functional_model")
            layer_configs = model_config["config"]
            if isinstance(layer_configs, dict):  # keras 2.3+: {"layers": []}
                layer_configs = layer_configs["layers"]
            conf = KerasModelImport._build_sequential_conf(layer_configs, loss)
            net = MultiLayerNetwork(conf).init()
            KerasModelImport._load_sequential_weights(f, net, layer_configs)
        return net

    @staticmethod
    def import_model(path: str, *, train: bool = False, loss: str = "mcxent"):
        """h5 → model, dispatching on the saved architecture class (parity:
        ``Model.importModel`` ``keras/Model.java:95-128``): Sequential →
        MultiLayerNetwork, Model/Functional → ComputationGraph."""
        import h5py

        with h5py.File(path, "r") as f:
            class_name = KerasModelImport._read_model_config(f)["class_name"]
        if class_name == "Sequential":
            return KerasModelImport.import_sequential_model(
                path, train=train, loss=loss)
        return KerasModelImport.import_functional_model(
            path, train=train, loss=loss)

    @staticmethod
    def import_functional_model(path: str, *, train: bool = False,
                                loss: str = "mcxent"):
        """h5 functional-API model → initialized ComputationGraph with
        imported weights (parity: ``Model.importFunctionalApiModel``
        ``keras/Model.java:78``).

        Keras merge layers map to graph vertices: Concatenate/Merge(concat) →
        MergeVertex, Add/Merge(sum) → ElementWiseVertex(add), Subtract →
        ElementWiseVertex(subtract), Multiply → ElementWiseVertex(product),
        Average → ElementWiseVertex(average), Maximum → ElementWiseVertex(max).
        Dense layers feeding network outputs become OutputLayers with `loss`
        so the returned graph is trainable/evaluable."""
        import h5py
        from ..nn.graph_runtime import ComputationGraph

        with h5py.File(path, "r") as f:
            model_config = KerasModelImport._read_model_config(f)
            class_name = model_config["class_name"]
            if class_name == "Sequential":
                raise ValueError(
                    "sequential model; use import_sequential_model")
            conf = KerasModelImport._build_functional_conf(
                model_config["config"], loss)
            net = ComputationGraph(conf).init()
            KerasModelImport._load_graph_weights(f, net, model_config)
        return net

    @staticmethod
    def import_model_configuration(path_or_json: str, loss: str = "mcxent"):
        """Config-only import: model JSON (file path or string) →
        MultiLayerConfiguration (parity: ``ModelConfiguration``)."""
        if path_or_json.lstrip().startswith("{"):
            model_config = json.loads(path_or_json)
        else:
            with open(path_or_json) as f:
                model_config = json.load(f)
        layer_configs = model_config["config"]
        if isinstance(layer_configs, dict):
            layer_configs = layer_configs["layers"]
        return KerasModelImport._build_sequential_conf(layer_configs, loss)

    # ------------------------------------------------------------------
    # config translation
    # ------------------------------------------------------------------

    @staticmethod
    def _read_model_config(f) -> dict:
        raw = f.attrs.get("model_config")
        if raw is None:
            raise ValueError("no model_config attribute — architecture JSON "
                             "required (weights-only files unsupported)")
        if isinstance(raw, bytes):
            raw = raw.decode("utf-8")
        return json.loads(raw)

    @staticmethod
    def _input_type_of(cfg: dict, data_format: str) -> Optional[InputType]:
        shape = cfg.get("batch_input_shape")
        if shape is not None:
            shape = [s for s in shape[1:]]  # drop batch
        elif cfg.get("input_shape") is not None:
            shape = list(cfg["input_shape"])
        elif cfg.get("input_dim") is not None:
            shape = [int(cfg["input_dim"])]
        else:
            return None
        if len(shape) == 1:
            return InputType.feed_forward(int(shape[0]))
        if len(shape) == 2:
            return InputType.recurrent(int(shape[1]),
                                       None if shape[0] is None else int(shape[0]))
        if len(shape) == 3:
            if data_format == "channels_first":
                c, h, w = shape
            else:
                h, w, c = shape
            return InputType.convolutional(int(h), int(w), int(c))
        raise ValueError(f"unsupported input shape {shape}")

    @staticmethod
    def _data_format(cfg: dict) -> str:
        v = cfg.get("data_format") or cfg.get("dim_ordering")
        if v in ("channels_first", "th"):
            return "channels_first"
        return "channels_last"

    @staticmethod
    def _build_sequential_conf(layer_configs: List[dict], loss: str):
        builder = NeuralNetConfiguration.builder().updater("sgd") \
            .learning_rate(0.01).list()
        input_type = None
        entries = []  # (keras_name, our_layer | None)
        last_dense_idx = -1
        for lc in layer_configs:
            cls = lc["class_name"]
            cfg = lc["config"] if "config" in lc else {}
            name = cfg.get("name") or lc.get("name") or cls.lower()
            fmt = KerasModelImport._data_format(cfg)
            if input_type is None:
                it = KerasModelImport._input_type_of(cfg, fmt)
                if it is not None:
                    input_type = it
            layer = KerasModelImport._translate_layer(cls, cfg, fmt)
            if layer is None:
                continue
            layers_out = layer if isinstance(layer, list) else [layer]
            for li, l in enumerate(layers_out):
                # aux layers (e.g. LastTimeStep) carry no keras weights —
                # suffix the name so weight lookup skips them
                entries.append((name if li == 0 else f"{name}__aux{li}",
                                cls if li == 0 else "_Aux", l))
            if cls in ("Dense", "TimeDistributedDense", "TimeDistributed"):
                last_dense_idx = len(entries) - 1
        if last_dense_idx >= 0:
            # final Dense → OutputLayer so the net can train/evaluate
            name, cls, dense = entries[last_dense_idx]
            is_last_param_layer = all(
                c in ("Activation", "Dropout") for _, c, _ in
                entries[last_dense_idx + 1:])
            if is_last_param_layer:
                act = dense.activation
                # a following Activation layer overrides
                for _, c, l in entries[last_dense_idx + 1:]:
                    if c == "Activation":
                        act = l.activation
                entries = entries[:last_dense_idx + 1]
                if cls == "Dense":
                    entries[last_dense_idx] = (name, "Dense", OutputLayer(
                        n_in=dense.n_in, n_out=dense.n_out, activation=act,
                        loss=loss))
                else:
                    # final time-distributed dense → RnnOutputLayer (the
                    # reference's per-timestep output path)
                    entries[last_dense_idx] = (name, cls, RnnOutputLayer(
                        n_in=dense.n_in, n_out=dense.n_out, activation=act,
                        loss=loss))
        lb = builder
        for _, _, layer in entries:
            lb = lb.layer(layer)
        if input_type is not None:
            lb = lb.set_input_type(input_type)
        conf = lb.build()
        conf._keras_layer_names = [n for n, _, _ in entries]
        conf._keras_classes = [c for _, c, _ in entries]
        return conf

    # merge-layer class → vertex factory (keras 2 classes + keras 1 Merge
    # modes; parity: the reference maps these onto MergeVertex /
    # ElementWiseVertex in KerasLayer handling, Model.java:78-128)
    _MERGE_OPS = {"Add": "add", "Subtract": "subtract",
                  "Multiply": "product", "Average": "average",
                  "Maximum": "max"}
    _MERGE1_MODES = {"sum": "add", "mul": "product", "ave": "average",
                     "max": "max"}

    @staticmethod
    def _build_functional_conf(config: dict, loss: str):
        from ..nn.conf.graph import ElementWiseVertex, MergeVertex

        layers = config["layers"]
        output_refs = [o[0] for o in config["output_layers"]]
        input_refs = [i[0] for i in config["input_layers"]]

        builder = (NeuralNetConfiguration.builder().updater("sgd")
                   .learning_rate(0.01).graph_builder())
        builder.add_inputs(*input_refs)

        alias: Dict[str, str] = {}   # keras name → actual vertex name
        input_types: Dict[str, InputType] = {}
        classes_by_name: Dict[str, str] = {}

        def resolve(name: str) -> str:
            while name in alias:
                name = alias[name]
            return name

        for lc in layers:
            cls = lc["class_name"]
            cfg = lc.get("config", {})
            name = lc.get("name") or cfg.get("name") or cls.lower()
            fmt = KerasModelImport._data_format(cfg)
            nodes = lc.get("inbound_nodes") or []
            in_names = [resolve(ref[0]) for ref in (nodes[0] if nodes else [])]

            if cls == "InputLayer":
                it = KerasModelImport._input_type_of(cfg, fmt)
                if it is not None:
                    input_types[name] = it
                continue

            if cls == "Concatenate" or (
                    cls == "Merge" and cfg.get("mode", "concat") == "concat"):
                builder.add_vertex(name, MergeVertex(), *in_names)
                classes_by_name[name] = cls
                continue
            if cls in KerasModelImport._MERGE_OPS or cls == "Merge":
                op = (KerasModelImport._MERGE_OPS.get(cls)
                      or KerasModelImport._MERGE1_MODES.get(cfg.get("mode")))
                if op is None:
                    raise ValueError(
                        f"unsupported Merge mode {cfg.get('mode')!r}")
                builder.add_vertex(name, ElementWiseVertex(op=op), *in_names)
                classes_by_name[name] = cls
                continue

            layer = KerasModelImport._translate_layer(cls, cfg, fmt)
            if layer is None:           # Flatten etc: pass-through alias
                alias[name] = in_names[0]
                continue
            if name in output_refs and cls == "Dense":
                # Dense at a network output → OutputLayer (trainable graph)
                layer = OutputLayer(n_out=layer.n_out,
                                    activation=layer.activation, loss=loss)
            layers_out = layer if isinstance(layer, list) else [layer]
            prev = in_names
            for li, l in enumerate(layers_out):
                vname = name if li == 0 else f"{name}__aux{li}"
                builder.add_layer(vname, l, *prev)
                classes_by_name[vname] = cls if li == 0 else "_Aux"
                prev = [vname]
            if len(layers_out) > 1:
                alias[name] = prev[0]   # downstream consumers see the aux tail
                classes_by_name[name] = cls  # weights live under keras name

        builder.set_outputs(*[resolve(o) for o in output_refs])
        if input_types:
            missing = [i for i in input_refs if i not in input_types]
            if missing:
                # positional set_input_types would silently assign shapes to
                # the wrong inputs — fail loudly instead
                raise ValueError(
                    f"InputLayer(s) {missing} declare no input shape while "
                    f"{sorted(input_types)} do; cannot infer input types")
            builder.set_input_types(*[input_types[i] for i in input_refs])
        conf = builder.build()
        conf._keras_classes_by_name = classes_by_name
        return conf

    @staticmethod
    def _merge_translated_weights(net, key, lname: str, p: dict) -> None:
        """Merge translated keras arrays into net.params[key] (running
        mean/var go to net.state) with shape validation. Shared by the
        sequential and functional loaders."""
        import jax.numpy as jnp
        cur = dict(net.params[key])
        for pname, arr in p.items():
            if pname in ("mean", "var"):
                st = dict(net.state.get(key, {}))
                st[pname] = jnp.asarray(arr)
                net.state[key] = st
            else:
                if pname in cur and tuple(cur[pname].shape) != tuple(arr.shape):
                    raise ValueError(
                        f"{lname}/{pname}: shape {arr.shape} != expected "
                        f"{cur[pname].shape}")
                cur[pname] = jnp.asarray(arr)
        net.params[key] = cur

    @staticmethod
    def _load_graph_weights(f, net, model_config: dict) -> None:
        """Copy keras weights into ComputationGraph params by VERTEX NAME
        (functional models address layers by name, reference Model.java:110)."""
        group = KerasModelImport._weight_group(f)
        classes = net.conf._keras_classes_by_name
        fmt_by_name = {}
        for lc in model_config["config"]["layers"]:
            c = lc.get("config", {})
            n = lc.get("name") or c.get("name")
            fmt_by_name[n] = KerasModelImport._data_format(c)
        for vname in net.topo_order:
            cls = classes.get(vname)
            if cls in (None, "_Aux"):
                continue
            arrays = KerasModelImport._layer_arrays(group, vname)
            if not arrays:
                continue
            p = KerasModelImport._translate_weights(
                cls, arrays, vname, fmt_by_name.get(vname, "channels_last"))
            if p:
                KerasModelImport._merge_translated_weights(net, vname, vname, p)

    @staticmethod
    def _translate_layer(cls: str, cfg: dict, fmt: str):
        act = _map_activation(cfg.get("activation"))
        if cls == "Dense":
            n_out = cfg.get("units") or cfg.get("output_dim")
            return DenseLayer(n_out=int(n_out), activation=act)
        if cls in ("Convolution2D", "Conv2D"):
            n_out = cfg.get("filters") or cfg.get("nb_filter")
            ks = cfg.get("kernel_size") or (cfg.get("nb_row"), cfg.get("nb_col"))
            stride = cfg.get("strides") or cfg.get("subsample") or (1, 1)
            pad = cfg.get("padding") or cfg.get("border_mode") or "valid"
            return ConvolutionLayer(n_out=int(n_out),
                                    kernel_size=tuple(int(k) for k in ks),
                                    stride=tuple(int(s) for s in stride),
                                    border_mode=str(pad), activation=act)
        if cls in ("MaxPooling2D", "AveragePooling2D"):
            ks = cfg.get("pool_size") or (2, 2)
            stride = cfg.get("strides") or ks
            pad = cfg.get("padding") or cfg.get("border_mode") or "valid"
            return SubsamplingLayer(
                pooling_type="max" if cls == "MaxPooling2D" else "avg",
                kernel_size=tuple(int(k) for k in ks),
                stride=tuple(int(s) for s in stride), border_mode=str(pad))
        if cls == "LSTM":
            n_out = cfg.get("units") or cfg.get("output_dim")
            lstm = GravesLSTM(
                n_out=int(n_out), activation=act if act != "identity" else "tanh",
                gate_activation=_map_activation(
                    cfg.get("recurrent_activation")
                    or cfg.get("inner_activation") or "hard_sigmoid"))
            if not cfg.get("return_sequences", False):
                return [lstm, LastTimeStepLayer()]
            return lstm
        if cls == "TimeDistributedDense":  # keras 1
            n_out = cfg.get("units") or cfg.get("output_dim")
            return TimeDistributedDenseLayer(n_out=int(n_out), activation=act)
        if cls == "TimeDistributed":  # keras 2 wrapper
            inner = cfg.get("layer") or {}
            if inner.get("class_name") != "Dense":
                raise ValueError(
                    "only TimeDistributed(Dense) import is supported, got "
                    f"TimeDistributed({inner.get('class_name')!r})")
            icfg = inner.get("config", {})
            n_out = icfg.get("units") or icfg.get("output_dim")
            return TimeDistributedDenseLayer(
                n_out=int(n_out),
                activation=_map_activation(icfg.get("activation")))
        if cls == "Embedding":
            return EmbeddingLayer(n_in=int(cfg["input_dim"]),
                                  n_out=int(cfg["output_dim"]),
                                  activation="identity", has_bias=False)
        if cls == "BatchNormalization":
            return BatchNormalization(eps=float(cfg.get("epsilon", 1e-5)),
                                      decay=float(cfg.get("momentum", 0.9)))
        if cls == "Activation":
            return ActivationLayer(activation=act)
        if cls == "Dropout":
            return DropoutLayer(dropout=float(cfg.get("rate", cfg.get("p", 0.0))))
        if cls in ("Flatten", "InputLayer"):
            return None  # shape handling is automatic (preprocessors)
        raise ValueError(f"unsupported Keras layer type {cls!r}")

    # ------------------------------------------------------------------
    # weight loading
    # ------------------------------------------------------------------

    @staticmethod
    def _weight_group(f):
        return f["model_weights"] if "model_weights" in f else f

    @staticmethod
    def _layer_arrays(group, lname: str) -> Dict[str, np.ndarray]:
        """All arrays under a keras layer group, keyed by trailing name
        (kernel/bias/...); falls back to keras-1 style flat names."""
        if lname not in group:
            return {}
        g = group[lname]
        out = {}

        def visit(name, obj):
            import h5py
            if isinstance(obj, h5py.Dataset):
                key = name.split("/")[-1].split(":")[0]
                out[key] = np.asarray(obj)
        g.visititems(visit)
        return out

    @staticmethod
    def _load_sequential_weights(f, net, layer_configs) -> None:
        group = KerasModelImport._weight_group(f)
        names = net.conf._keras_layer_names
        classes = net.conf._keras_classes
        for i, (lname, cls) in enumerate(zip(names, classes)):
            arrays = KerasModelImport._layer_arrays(group, lname)
            if not arrays:
                continue
            key = f"layer_{i}"
            fmt = "channels_last"
            for lc in layer_configs:
                c = lc.get("config", {})
                if (c.get("name") or lc.get("name")) == lname:
                    fmt = KerasModelImport._data_format(c)
            p = KerasModelImport._translate_weights(cls, arrays, lname, fmt)
            if p:
                KerasModelImport._merge_translated_weights(net, key, lname, p)

    @staticmethod
    def _translate_weights(cls: str, arrays: Dict[str, np.ndarray],
                           lname: str, fmt: str) -> Dict[str, np.ndarray]:
        a = arrays
        if cls in ("Dense", "TimeDistributedDense", "TimeDistributed"):
            # TimeDistributed(Dense) stores plain Dense kernel/bias under
            # the wrapper layer's name
            out = {}
            k = a.get("kernel", a.get(f"{lname}_W"))
            b = a.get("bias", a.get(f"{lname}_b"))
            if k is not None:
                out["W"] = k  # keras Dense kernel is [in, out] — ours too
            if b is not None:
                out["b"] = b
            return out
        if cls in ("Convolution2D", "Conv2D"):
            k = a.get("kernel", a.get(f"{lname}_W"))
            b = a.get("bias", a.get(f"{lname}_b"))
            out = {}
            if k is not None:
                if k.ndim == 4 and fmt == "channels_first":
                    k = np.transpose(k, (2, 3, 1, 0))  # OIHW → HWIO
                out["W"] = k  # tf format already HWIO
            if b is not None:
                out["b"] = b
            return out
        if cls == "LSTM":
            return KerasModelImport._translate_lstm(a, lname)
        if cls == "Embedding":
            k = a.get("embeddings", a.get(f"{lname}_W"))
            return {} if k is None else {"W": k}
        if cls == "BatchNormalization":
            out = {}
            for src, dst in (("gamma", "gamma"), ("beta", "beta"),
                             ("moving_mean", "mean"),
                             ("moving_variance", "var")):
                v = a.get(src, a.get(f"{lname}_{src}"))
                if v is not None:
                    out[dst] = v
            return out
        return {}

    @staticmethod
    def _translate_lstm(a: Dict[str, np.ndarray], lname: str
                        ) -> Dict[str, np.ndarray]:
        """Keras LSTM → our [a|i|f|o]-concatenated layout (a = keras 'c'
        candidate). Keras 2: kernel [in, 4H] gate order i,f,c,o. Keras 1:
        separate W_i/U_i/b_i per gate."""
        def reorder(k):  # [.., 4H] i,f,c,o → a,i,f,o
            H = k.shape[-1] // 4
            i, f, c, o = (k[..., :H], k[..., H:2 * H],
                          k[..., 2 * H:3 * H], k[..., 3 * H:])
            return np.concatenate([c, i, f, o], axis=-1)

        if "kernel" in a:  # keras 2
            out = {"W": reorder(a["kernel"]),
                   "RW": reorder(a["recurrent_kernel"])}
            if "bias" in a:
                out["b"] = reorder(a["bias"])
            return out
        # keras 1: per-gate arrays
        def get(g, kind):
            return a.get(f"{lname}_{kind}_{g}", a.get(f"{kind}_{g}"))
        gates = ["c", "i", "f", "o"]
        W = np.concatenate([get(g, "W") for g in gates], axis=-1)
        RW = np.concatenate([get(g, "U") for g in gates], axis=-1)
        b = np.concatenate([get(g, "b") for g in gates], axis=-1)
        return {"W": W, "RW": RW, "b": b}
