"""t-SNE (parity: reference ``plot/Tsne.java`` exact version and
``plot/BarnesHutTsne.java``).

TPU-native design: the exact O(n²) formulation IS the TPU-friendly one — the
[n, n] affinity/repulsion matrices are dense batched ops that XLA tiles onto
the MXU, and for the n ≤ ~20k regime t-SNE is used in (visualizing embedding
tables), a dense jitted step beats host-side Barnes-Hut tree walks by a wide
margin. ``BarnesHutTsne`` therefore keeps the reference's API (theta,
perplexity, momentum/lr schedule, PCA init) but runs the dense jitted path —
theta is accepted for API parity and the gradient is exact (θ→0 limit).

Perplexity calibration (binary search for per-point sigmas) is vectorized
over all points at once in one jitted while-loop.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np


@functools.partial(__import__("jax").jit, static_argnames=("perplexity",))
def _calibrated_P(x, *, perplexity):
    """Conditional P matrix via vectorized binary search on sigma."""
    import jax
    import jax.numpy as jnp

    n = x.shape[0]
    x2 = jnp.sum(x * x, axis=1)
    d2 = x2[:, None] + x2[None, :] - 2.0 * (x @ x.T)
    d2 = d2.at[jnp.arange(n), jnp.arange(n)].set(0.0)
    log_u = jnp.log(jnp.float32(perplexity))

    def entropy_and_p(beta):
        # beta: [n, 1] precision per point
        logits = -d2 * beta
        logits = logits.at[jnp.arange(n), jnp.arange(n)].set(-jnp.inf)
        p = jax.nn.softmax(logits, axis=1)
        h = -jnp.sum(jnp.where(p > 1e-12, p * jnp.log(p), 0.0), axis=1)
        return h, p

    def body(state):
        beta, lo, hi, _ = state
        h, p = entropy_and_p(beta)
        too_high = h > log_u            # entropy too high → raise beta
        new_lo = jnp.where(too_high, beta, lo)
        new_hi = jnp.where(too_high, hi, beta)
        new_beta = jnp.where(
            too_high,
            jnp.where(jnp.isinf(new_hi), beta * 2.0, (beta + new_hi) / 2.0),
            jnp.where(new_lo <= 0.0, beta / 2.0, (beta + new_lo) / 2.0))
        return new_beta, new_lo, new_hi, p

    beta = jnp.ones((n, 1), jnp.float32)
    lo = jnp.zeros((n, 1), jnp.float32)
    hi = jnp.full((n, 1), jnp.inf, jnp.float32)
    state = (beta, lo, hi, jnp.zeros((n, n), jnp.float32))
    for _ in range(40):  # fixed-iteration binary search (compiles once)
        state = body(state)
    p = state[3]
    p = (p + p.T) / (2.0 * n)
    return jnp.maximum(p, 1e-12)


@functools.partial(__import__("jax").jit)
def _tsne_grad(y, P):
    import jax.numpy as jnp
    n = y.shape[0]
    y2 = jnp.sum(y * y, axis=1)
    num = 1.0 / (1.0 + y2[:, None] + y2[None, :] - 2.0 * (y @ y.T))
    num = num.at[jnp.arange(n), jnp.arange(n)].set(0.0)
    Q = jnp.maximum(num / jnp.sum(num), 1e-12)
    PQ = (P - Q) * num
    grad = 4.0 * ((jnp.diag(jnp.sum(PQ, axis=1)) - PQ) @ y)
    kl = jnp.sum(P * jnp.log(P / Q))
    return grad, kl


class Tsne:
    """Exact t-SNE (reference builder knobs: ``perplexity``,
    ``learningRate``, ``maxIter``, momentum switch, early exaggeration)."""

    def __init__(self, *, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, max_iter: int = 500,
                 early_exaggeration: float = 12.0, exaggeration_iters: int = 100,
                 initial_momentum: float = 0.5, final_momentum: float = 0.8,
                 momentum_switch: int = 250, seed: int = 42,
                 use_pca_init: bool = True):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.early_exaggeration = early_exaggeration
        self.exaggeration_iters = exaggeration_iters
        self.initial_momentum = initial_momentum
        self.final_momentum = final_momentum
        self.momentum_switch = momentum_switch
        self.seed = seed
        self.use_pca_init = use_pca_init
        self.embedding: Optional[np.ndarray] = None
        self.kl_divergence: Optional[float] = None

    def fit_transform(self, x) -> np.ndarray:
        import jax.numpy as jnp

        x = np.asarray(x, dtype=np.float32)
        n = x.shape[0]
        if n - 1 < 3 * self.perplexity:
            raise ValueError(
                f"perplexity {self.perplexity} too large for n={n} "
                "(need n-1 >= 3*perplexity)")
        P = _calibrated_P(jnp.asarray(x), perplexity=self.perplexity)

        rng = np.random.default_rng(self.seed)
        if self.use_pca_init and x.shape[1] > self.n_components:
            xc = x - x.mean(axis=0)
            _, _, vt = np.linalg.svd(xc, full_matrices=False)
            y0 = (xc @ vt[:self.n_components].T) * 1e-2
        else:
            y0 = rng.normal(0, 1e-4, size=(n, self.n_components))
        y = jnp.asarray(y0.astype(np.float32))
        vel = jnp.zeros_like(y)
        kl = None
        for it in range(self.max_iter):
            Pi = P * self.early_exaggeration \
                if it < self.exaggeration_iters else P
            grad, kl = _tsne_grad(y, Pi)
            mom = self.initial_momentum if it < self.momentum_switch \
                else self.final_momentum
            vel = mom * vel - self.learning_rate * grad
            y = y + vel
            y = y - jnp.mean(y, axis=0)
        self.embedding = np.asarray(y)
        self.kl_divergence = float(kl) if kl is not None else None
        return self.embedding


class BarnesHutTsne(Tsne):
    """Reference-API-compatible wrapper (``theta`` accepted; gradient is
    exact — see module docstring for why dense-on-TPU replaces the SpTree
    approximation)."""

    def __init__(self, *, theta: float = 0.5, **kw):
        super().__init__(**kw)
        self.theta = theta
