"""t-SNE (parity: reference ``plot/Tsne.java`` exact version and
``plot/BarnesHutTsne.java``).

Two regimes, both real:

- **Dense exact** (:class:`Tsne`, and BarnesHutTsne with ``theta=0`` or
  small n): the [n, n] affinity/repulsion matrices are dense batched ops
  XLA tiles onto the MXU — at n ≤ ~10k this beats tree walks outright.
- **Barnes-Hut** (:class:`BarnesHutTsne`, ``theta>0``): O(uN) sparse input
  similarities from k-nearest-neighbors (k = 3·perplexity, reference
  ``BarnesHutTsne.java`` via VPTree) + O(N log N) repulsion through a real
  SpTree (``clustering/sptree.py``; hot path in C++ via
  ``clustering/native.py`` — the reference ran this loop in JIT-compiled
  Java, Python walks are ~100× too slow).

Perplexity calibration (binary search for per-point sigmas) is vectorized
over all points at once — dense path in one jitted loop, BH path over the
kNN distance matrix in numpy.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np


@functools.partial(__import__("jax").jit, static_argnames=("perplexity",))
def _calibrated_P(x, *, perplexity):
    """Conditional P matrix via vectorized binary search on sigma."""
    import jax
    import jax.numpy as jnp

    n = x.shape[0]
    x2 = jnp.sum(x * x, axis=1)
    d2 = x2[:, None] + x2[None, :] - 2.0 * (x @ x.T)
    d2 = d2.at[jnp.arange(n), jnp.arange(n)].set(0.0)
    log_u = jnp.log(jnp.float32(perplexity))

    def entropy_and_p(beta):
        # beta: [n, 1] precision per point
        logits = -d2 * beta
        logits = logits.at[jnp.arange(n), jnp.arange(n)].set(-jnp.inf)
        p = jax.nn.softmax(logits, axis=1)
        h = -jnp.sum(jnp.where(p > 1e-12, p * jnp.log(p), 0.0), axis=1)
        return h, p

    def body(state):
        beta, lo, hi, _ = state
        h, p = entropy_and_p(beta)
        too_high = h > log_u            # entropy too high → raise beta
        new_lo = jnp.where(too_high, beta, lo)
        new_hi = jnp.where(too_high, hi, beta)
        new_beta = jnp.where(
            too_high,
            jnp.where(jnp.isinf(new_hi), beta * 2.0, (beta + new_hi) / 2.0),
            jnp.where(new_lo <= 0.0, beta / 2.0, (beta + new_lo) / 2.0))
        return new_beta, new_lo, new_hi, p

    beta = jnp.ones((n, 1), jnp.float32)
    lo = jnp.zeros((n, 1), jnp.float32)
    hi = jnp.full((n, 1), jnp.inf, jnp.float32)
    state = (beta, lo, hi, jnp.zeros((n, n), jnp.float32))
    for _ in range(40):  # fixed-iteration binary search (compiles once)
        state = body(state)
    p = state[3]
    p = (p + p.T) / (2.0 * n)
    return jnp.maximum(p, 1e-12)


@functools.partial(__import__("jax").jit)
def _tsne_grad(y, P):
    import jax.numpy as jnp
    n = y.shape[0]
    y2 = jnp.sum(y * y, axis=1)
    num = 1.0 / (1.0 + y2[:, None] + y2[None, :] - 2.0 * (y @ y.T))
    num = num.at[jnp.arange(n), jnp.arange(n)].set(0.0)
    Q = jnp.maximum(num / jnp.sum(num), 1e-12)
    PQ = (P - Q) * num
    grad = 4.0 * ((jnp.diag(jnp.sum(PQ, axis=1)) - PQ) @ y)
    kl = jnp.sum(P * jnp.log(P / Q))
    return grad, kl


class Tsne:
    """Exact t-SNE (reference builder knobs: ``perplexity``,
    ``learningRate``, ``maxIter``, momentum switch, early exaggeration)."""

    def __init__(self, *, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, max_iter: int = 500,
                 early_exaggeration: float = 12.0, exaggeration_iters: int = 100,
                 initial_momentum: float = 0.5, final_momentum: float = 0.8,
                 momentum_switch: int = 250, seed: int = 42,
                 use_pca_init: bool = True):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.early_exaggeration = early_exaggeration
        self.exaggeration_iters = exaggeration_iters
        self.initial_momentum = initial_momentum
        self.final_momentum = final_momentum
        self.momentum_switch = momentum_switch
        self.seed = seed
        self.use_pca_init = use_pca_init
        self.embedding: Optional[np.ndarray] = None
        self.kl_divergence: Optional[float] = None

    def fit_transform(self, x) -> np.ndarray:
        import jax.numpy as jnp

        x = np.asarray(x, dtype=np.float32)
        n = x.shape[0]
        if n - 1 < 3 * self.perplexity:
            raise ValueError(
                f"perplexity {self.perplexity} too large for n={n} "
                "(need n-1 >= 3*perplexity)")
        P = _calibrated_P(jnp.asarray(x), perplexity=self.perplexity)

        rng = np.random.default_rng(self.seed)
        if self.use_pca_init and x.shape[1] > self.n_components:
            xc = x - x.mean(axis=0)
            _, _, vt = np.linalg.svd(xc, full_matrices=False)
            y0 = (xc @ vt[:self.n_components].T) * 1e-2
        else:
            y0 = rng.normal(0, 1e-4, size=(n, self.n_components))
        y = jnp.asarray(y0.astype(np.float32))
        vel = jnp.zeros_like(y)
        kl = None
        for it in range(self.max_iter):
            Pi = P * self.early_exaggeration \
                if it < self.exaggeration_iters else P
            grad, kl = _tsne_grad(y, Pi)
            mom = self.initial_momentum if it < self.momentum_switch \
                else self.final_momentum
            vel = mom * vel - self.learning_rate * grad
            y = y + vel
            y = y - jnp.mean(y, axis=0)
        self.embedding = np.asarray(y)
        self.kl_divergence = float(kl) if kl is not None else None
        return self.embedding


def _knn_sparse_p(x: np.ndarray, perplexity: float, k: int
                  ) -> "tuple[np.ndarray, np.ndarray, np.ndarray]":
    """Symmetrized sparse input similarities over k nearest neighbors
    (parity: ``BarnesHutTsne.computeGaussianPerplexity`` sparse variant).
    Returns CSR (row_ptr, cols, vals); vals sum to 1."""
    n = x.shape[0]
    k = min(k, n - 1)
    # chunked exact kNN (the reference uses a VPTree; brute-force chunks are
    # simpler and BLAS-fast at the n this path serves)
    x2 = np.sum(x * x, axis=1)
    nbr = np.empty((n, k), dtype=np.int64)
    nbr_d2 = np.empty((n, k), dtype=np.float64)
    chunk = max(1, int(2e8 // max(n, 1)))
    for s in range(0, n, chunk):
        e = min(n, s + chunk)
        d2 = x2[s:e, None] + x2[None, :] - 2.0 * (x[s:e] @ x.T)
        np.fill_diagonal(d2[:, s:e], np.inf)
        idx = np.argpartition(d2, k, axis=1)[:, :k]
        part = np.take_along_axis(d2, idx, axis=1)
        order = np.argsort(part, axis=1)
        nbr[s:e] = np.take_along_axis(idx, order, axis=1)
        nbr_d2[s:e] = np.take_along_axis(part, order, axis=1)
    # per-point beta binary search on the kNN distances
    log_u = np.log(perplexity)
    beta = np.ones(n)
    lo = np.zeros(n)
    hi = np.full(n, np.inf)
    P = np.zeros((n, k))
    for _ in range(50):
        logits = -nbr_d2 * beta[:, None]
        logits -= logits.max(axis=1, keepdims=True)
        expd = np.exp(logits)
        P = expd / expd.sum(axis=1, keepdims=True)
        H = -np.sum(np.where(P > 1e-12, P * np.log(P), 0.0), axis=1)
        too_high = H > log_u
        lo = np.where(too_high, beta, lo)
        hi = np.where(too_high, hi, beta)
        beta = np.where(
            too_high,
            np.where(np.isinf(hi), beta * 2.0, (beta + hi) / 2.0),
            np.where(lo <= 0.0, beta / 2.0, (beta + lo) / 2.0))
    # symmetrize: P_ij = (P_j|i + P_i|j) / 2n over the union of edges
    from collections import defaultdict
    sym: "defaultdict[tuple, float]" = defaultdict(float)
    for i in range(n):
        for c in range(k):
            j = int(nbr[i, c])
            v = P[i, c] / (2.0 * n)
            sym[(i, j)] += v
            sym[(j, i)] += v
    rows = np.fromiter((ij[0] for ij in sym), dtype=np.int64, count=len(sym))
    cols = np.fromiter((ij[1] for ij in sym), dtype=np.int64, count=len(sym))
    vals = np.fromiter(sym.values(), dtype=np.float64, count=len(sym))
    order = np.lexsort((cols, rows))
    rows, cols, vals = rows[order], cols[order], vals[order]
    row_ptr = np.zeros(n + 1, dtype=np.int64)
    np.add.at(row_ptr, rows + 1, 1)
    row_ptr = np.cumsum(row_ptr)
    return row_ptr, cols, vals


def _bh_gradient_python(y, row_ptr, cols, vals, theta):
    """Pure-Python BH gradient via clustering.sptree (oracle/fallback)."""
    from ..clustering.sptree import SpTree
    n, d = y.shape
    tree = SpTree(y)
    neg = np.zeros((n, d))
    sum_q = 0.0
    for i in range(n):
        f, q = tree.compute_non_edge_forces(i, theta)
        neg[i] = f
        sum_q += q
    sum_q = max(sum_q, 1e-12)
    pos = np.zeros((n, d))
    kl = 0.0
    for i in range(n):
        for e in range(row_ptr[i], row_ptr[i + 1]):
            j = cols[e]
            diff = y[i] - y[j]
            q = 1.0 / (1.0 + diff @ diff)
            pos[i] += vals[e] * q * diff
            qn = max(q / sum_q, 1e-12)
            if vals[e] > 1e-12:
                kl += vals[e] * np.log(vals[e] / qn)
    return 4.0 * (pos - neg / sum_q), kl


class BarnesHutTsne(Tsne):
    """Barnes-Hut t-SNE (parity: ``plot/BarnesHutTsne.java``): sparse kNN
    input similarities + SpTree-approximated repulsion, O(N log N) per
    iteration. ``theta=0`` (or n ≤ ``dense_threshold``) falls back to the
    exact dense jitted path, which is faster on TPU at small n."""

    def __init__(self, *, theta: float = 0.5, dense_threshold: int = 2048,
                 **kw):
        super().__init__(**kw)
        self.theta = theta
        self.dense_threshold = int(dense_threshold)

    def fit_transform(self, x) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        n = x.shape[0]
        if self.theta <= 0.0 or n <= self.dense_threshold:
            return super().fit_transform(x)
        if n - 1 < 3 * self.perplexity:
            raise ValueError(
                f"perplexity {self.perplexity} too large for n={n}")
        k = int(3 * self.perplexity)
        row_ptr, cols, vals = _knn_sparse_p(x, self.perplexity, k)

        rng = np.random.default_rng(self.seed)
        if self.use_pca_init and x.shape[1] > self.n_components:
            xc = x - x.mean(axis=0)
            _, _, vt = np.linalg.svd(xc, full_matrices=False)
            y = (xc @ vt[:self.n_components].T) * 1e-2
        else:
            y = rng.normal(0, 1e-4, size=(n, self.n_components))
        y = np.ascontiguousarray(y, dtype=np.float64)
        vel = np.zeros_like(y)

        from ..clustering import native
        use_native = native.load() is not None
        kl = None
        for it in range(self.max_iter):
            scale = (self.early_exaggeration
                     if it < self.exaggeration_iters else 1.0)
            v = vals * scale
            if use_native:
                grad, kl = native.bh_gradient(y, row_ptr, cols, v,
                                              self.theta)
            else:
                grad, kl = _bh_gradient_python(y, row_ptr, cols, v,
                                               self.theta)
            mom = (self.initial_momentum if it < self.momentum_switch
                   else self.final_momentum)
            vel = mom * vel - self.learning_rate * grad
            y = y + vel
            y = y - y.mean(axis=0)
        self.embedding = np.asarray(y, dtype=np.float32)
        self.kl_divergence = float(kl) if kl is not None else None
        return self.embedding
