"""Visualization embeddings: t-SNE.

Parity: reference ``plot/Tsne.java`` (exact) and ``plot/BarnesHutTsne.java``
(θ-approximate via SpTree).
"""

from .tsne import BarnesHutTsne, Tsne

__all__ = ["Tsne", "BarnesHutTsne"]
