"""Benchmark: ResNet-50 (headline, BASELINE.md config #2) + LeNet (config #1)
training throughput on the real TPU chip.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}``.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` reports
measured MFU / the 40% MFU north-star target (BASELINE.json). Extra keys
carry the raw numbers for both configs.

Both configs train via the scan-fused path (K steps per dispatch) — the
framework's idiomatic TPU inner loop, which also amortizes the dev-tunnel's
~100ms per-dispatch RPC latency out of the measurement.
"""

from __future__ import annotations

import json
import os
import time
import traceback

import numpy as np


def _peak_flops_per_sec() -> float:
    """Per-chip peak (bf16). TPU v5e ≈ 197 TFLOP/s."""
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v4" in kind:
        return 275e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v6" in kind:
        return 918e12
    return 197e12


def _conv_flops_nhwc(h, w, c_in, c_out, kh, kw, stride):
    oh, ow = -(-h // stride), -(-w // stride)
    return 2.0 * oh * ow * c_out * kh * kw * c_in, oh, ow


def _resnet50_train_flops_per_example(image=224, n_classes=1000) -> float:
    """Analytic fwd FLOPs for standard bottleneck ResNet-50 (≈4.1 GFLOP fwd
    at 224², matching the published figure); train ≈ 3× fwd."""
    total = 0.0
    f, h = 0.0, image
    # stem 7x7/2 ch 3->64
    f, oh, _ = _conv_flops_nhwc(h, h, 3, 64, 7, 7, 2)
    total += f
    h = oh
    h = -(-h // 2)  # maxpool /2
    c_in = 64
    for stage, (planes, blocks) in enumerate(
            [(64, 3), (128, 4), (256, 6), (512, 3)]):
        for i in range(blocks):
            stride = 2 if (stage > 0 and i == 0) else 1
            oh = -(-h // stride)
            # 1x1 reduce (at input res), 3x3 (stride), 1x1 expand
            f1, _, _ = _conv_flops_nhwc(h, h, c_in, planes, 1, 1, 1)
            f2, _, _ = _conv_flops_nhwc(h, h, planes, planes, 3, 3, stride)
            f3, _, _ = _conv_flops_nhwc(oh, oh, planes, planes * 4, 1, 1, 1)
            total += f1 + f2 + f3
            if i == 0:
                fp, _, _ = _conv_flops_nhwc(h, h, c_in, planes * 4, 1, 1, stride)
                total += fp
            c_in = planes * 4
            h = oh
    total += 2.0 * c_in * n_classes  # fc head
    return 3.0 * total


def _lenet_train_flops_per_example() -> float:
    fwd = (2.0 * 24 * 24 * 20 * 5 * 5 * 1      # conv1
           + 2.0 * 8 * 8 * 50 * 5 * 5 * 20     # conv2
           + 2.0 * 800 * 500                   # dense
           + 2.0 * 500 * 10)                   # out
    return 3.0 * fwd


def _stage_batches(k, batch, shape, n_classes, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(k, batch) + shape).astype(np.float32)
    ys = np.eye(n_classes, dtype=np.float32)[
        rng.integers(0, n_classes, (k, batch))]
    return xs, ys


def _time_scan(net, xs, ys, rounds) -> float:
    # NB: np.asarray (device→host transfer) is the completion barrier;
    # block_until_ready returns early through the axon dev tunnel.
    np.asarray(net.fit_scan(xs, ys))  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(rounds):
        losses = net.fit_scan(xs, ys)
    np.asarray(losses)
    return time.perf_counter() - t0


def bench_lenet() -> dict:
    import jax
    from deeplearning4j_tpu.models import lenet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch, k, rounds = 512, 32, 4
    net = MultiLayerNetwork(lenet()).init()
    xs, ys = _stage_batches(k, batch, (784,), 10, seed=7)
    xs, ys = jax.device_put(xs), jax.device_put(ys)
    dt = _time_scan(net, xs, ys, rounds)
    steps = rounds * k
    eps = steps * batch / dt
    mfu = eps * _lenet_train_flops_per_example() / _peak_flops_per_sec()
    return {"examples_per_sec": round(eps, 1), "mfu": round(mfu, 4),
            "step_ms": round(1000 * dt / steps, 3), "batch": batch}


def bench_resnet50() -> dict:
    """ResNet-50 training MFU. The K-step inner loop closes over ONE staged
    device batch (lax.scan over step indices), so arbitrarily long on-chip
    runs cost one batch of HBM — the measurement isolates train-step compute
    the way a production input pipeline (prefetching while computing) would."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models import resnet50
    from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
    from deeplearning4j_tpu.optimize import updaters as _updaters
    from deeplearning4j_tpu import rng as _rng

    image = int(os.environ.get("BENCH_RESNET_IMAGE", "224"))
    batch = int(os.environ.get("BENCH_RESNET_BATCH", "128"))
    k = int(os.environ.get("BENCH_RESNET_SCAN", "32"))
    rounds = 2
    conf = resnet50(height=image, width=image,
                    dtype=os.environ.get("BENCH_RESNET_DTYPE", "mixed_bf16"))
    net = ComputationGraph(conf).init()
    xs, ys = _stage_batches(1, batch, (image, image, 3), 1000, seed=11)
    x = jax.device_put(xs[0])
    y = jax.device_put(ys[0])

    t = net.training
    updater = net._updater
    base_key = _rng.key(t.seed)

    def k_steps(params, opt_state, states, x, y):
        def one(carry, i):
            params, opt_state, states = carry
            rng = jax.random.fold_in(base_key, i)
            (loss, new_states), grads = jax.value_and_grad(
                net._loss_fn, has_aux=True)(
                    params, states, [x], [y], None, rng)
            deltas, opt_state = updater.update(grads, opt_state, i)
            params = _updaters.apply_updates(params, deltas)
            kept = {name: {kk: new_states[name].get(kk, v)
                           for kk, v in st.items()}
                    for name, st in states.items()}
            return (params, opt_state, kept), loss
        (params, opt_state, states), losses = jax.lax.scan(
            one, (params, opt_state, states), jnp.arange(k))
        return params, opt_state, states, losses

    step = jax.jit(k_steps, donate_argnums=(0, 1))
    params, opt_state, states = net.params, net.updater_state, net._states_map()
    params, opt_state, states, losses = step(params, opt_state, states, x, y)
    np.asarray(losses)  # warmup/compile; host transfer = completion barrier
    t0 = time.perf_counter()
    for _ in range(rounds):
        params, opt_state, states, losses = step(params, opt_state, states, x, y)
    np.asarray(losses)
    dt = time.perf_counter() - t0

    steps = rounds * k
    eps = steps * batch / dt
    mfu = (eps * _resnet50_train_flops_per_example(image)
           / _peak_flops_per_sec())
    return {"examples_per_sec": round(eps, 1), "mfu": round(mfu, 4),
            "step_ms": round(1000 * dt / steps, 3), "batch": batch,
            "image": image}


def main() -> None:
    import jax
    device = str(jax.devices()[0].device_kind)
    out = {"device": device}
    lenet_res = None
    try:
        lenet_res = bench_lenet()
        out["lenet"] = lenet_res
    except Exception:
        out["lenet_error"] = traceback.format_exc(limit=2)
    resnet_res = None
    if os.environ.get("BENCH_SKIP_RESNET") != "1":
        try:
            resnet_res = bench_resnet50()
            out["resnet50"] = resnet_res
        except Exception:
            out["resnet50_error"] = traceback.format_exc(limit=2)

    if resnet_res is not None:
        out.update({
            "metric": "resnet50_train_throughput_per_chip",
            "value": resnet_res["examples_per_sec"],
            "unit": "examples/sec",
            "vs_baseline": round(resnet_res["mfu"] / 0.40, 4),
        })
    elif lenet_res is not None:
        out.update({
            "metric": "lenet_mnist_train_throughput",
            "value": lenet_res["examples_per_sec"],
            "unit": "examples/sec",
            "vs_baseline": round(lenet_res["mfu"] / 0.40, 4),
        })
    else:
        out.update({"metric": "bench_failed", "value": 0.0,
                    "unit": "examples/sec", "vs_baseline": 0.0})
    print(json.dumps(out))


if __name__ == "__main__":
    main()
