"""Benchmarks for the BASELINE.md configs on the real TPU chip.

Configs measured (BASELINE.md):
  #1 LeNet-5 MNIST        (MultiLayerNetwork.fit_repeated)
  #2 ResNet-50 ImageNet   (ComputationGraph.fit_repeated — the headline MFU
                           number) + a pipeline-fed variant (AsyncDataSetIterator
                           device prefetch feeding fit_scan via the public API)
  #3 char-RNN GravesLSTM  (MultiLayerNetwork.fit_repeated, tokens/s)
  #4 Word2Vec SGNS        (nlp.learning.ns_step_scan, pairs/s)

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}``.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` reports
measured MFU / the 40% MFU north-star target (BASELINE.json).

All measured loops run through the framework's PUBLIC APIs (fit_repeated /
fit_scan / ns_step_scan): K updates fused into one XLA dispatch, which is the
idiomatic TPU inner loop and also amortizes the dev-tunnel's ~100ms
per-dispatch RPC latency out of the measurement.

Every config runs under a retry wrapper: transient dev-tunnel RPC failures
(e.g. ``remote_compile: read body``) must never erase a round's evidence.
"""

from __future__ import annotations

import json
import os
import time
import traceback

import numpy as np

RETRIES = int(os.environ.get("BENCH_RETRIES", "3"))


def _run_config(out: dict, name: str, fn) -> dict | None:
    """Run one bench config with retries around transient device/RPC errors.

    Success: result dict stored at out[name] (with attempt count if >1).
    All attempts failed: traceback stored at out[f"{name}_error"].
    """
    last = None
    for attempt in range(1, RETRIES + 1):
        try:
            res = fn()
            if attempt > 1:
                res["attempts"] = attempt
            out[name] = res
            return res
        except Exception:
            last = traceback.format_exc(limit=3)
            if attempt < RETRIES:
                time.sleep(2.0 * attempt)
    out[f"{name}_error"] = last
    return None


def _peak_flops_per_sec() -> float:
    """Per-chip peak (bf16) — single source of truth in util/profiling.py.
    Unknown device kinds (CPU harness) return None there; the bench's MFU
    columns then assume this harness's chip so the ratio trajectory stays
    comparable across rounds."""
    from deeplearning4j_tpu.util import profiling
    peak = profiling.peak_flops_per_sec()
    return peak if peak is not None else 197e12  # assume v5e


MFU_DEVIATION_WARN_PCT = 15.0


def _mfu_crosscheck(fn_name: str, analytic_flops: float) -> dict:
    """Measured-vs-analytic FLOPs cross-check for one benched program:
    compares the compiled executable's HLO cost-analysis FLOPs
    (``compiled_flops{fn}``, recorded by the retrace guard at compile
    time) against the analytic formula's per-dispatch FLOPs. A deviation
    beyond ``MFU_DEVIATION_WARN_PCT`` means the analytic formula (the MFU
    numerator every PERF.md claim uses) has drifted from what the
    compiler actually builds — flagged in the payload AND logged, so
    formula rot is caught mechanically."""
    from deeplearning4j_tpu.util import metrics as _metrics
    out = {"analytic_flops_per_dispatch": analytic_flops}
    g = _metrics.REGISTRY.get("compiled_flops")
    measured = g.value(fn=fn_name) if g is not None else 0.0
    if not measured:
        out["flops_crosscheck"] = "unavailable"
        return out
    dev_pct = 100.0 * (measured - analytic_flops) / analytic_flops
    out.update({
        "compiled_flops_per_dispatch": measured,
        "flops_deviation_pct": round(dev_pct, 2),
        "flops_deviation_exceeds_warn": abs(dev_pct) > MFU_DEVIATION_WARN_PCT,
    })
    if abs(dev_pct) > MFU_DEVIATION_WARN_PCT:
        print(f"WARNING: {fn_name} measured FLOPs deviate "
              f"{dev_pct:+.1f}% from the analytic formula "
              f"(>{MFU_DEVIATION_WARN_PCT:.0f}%) — the MFU numerator has "
              "drifted; re-derive the formula against the compiled "
              "program", flush=True)
    return out


def _conv_flops_nhwc(h, w, c_in, c_out, kh, kw, stride):
    oh, ow = -(-h // stride), -(-w // stride)
    return 2.0 * oh * ow * c_out * kh * kw * c_in, oh, ow


def _resnet50_train_flops_per_example(image=224, n_classes=1000) -> float:
    """Analytic fwd FLOPs for standard bottleneck ResNet-50 — ≈8.2 GFLOP
    fwd at 224² (2 FLOPs per MAC × the published ≈4.1 GMACs); train ≈ 3×
    fwd. Peak in the MFU denominator uses the same 2-FLOPs-per-MAC
    convention, so the ratio is convention-consistent."""
    total = 0.0
    f, h = 0.0, image
    # stem 7x7/2 ch 3->64
    f, oh, _ = _conv_flops_nhwc(h, h, 3, 64, 7, 7, 2)
    total += f
    h = oh
    h = -(-h // 2)  # maxpool /2
    c_in = 64
    for stage, (planes, blocks) in enumerate(
            [(64, 3), (128, 4), (256, 6), (512, 3)]):
        for i in range(blocks):
            stride = 2 if (stage > 0 and i == 0) else 1
            oh = -(-h // stride)
            # 1x1 reduce (at input res), 3x3 (stride), 1x1 expand
            f1, _, _ = _conv_flops_nhwc(h, h, c_in, planes, 1, 1, 1)
            f2, _, _ = _conv_flops_nhwc(h, h, planes, planes, 3, 3, stride)
            f3, _, _ = _conv_flops_nhwc(oh, oh, planes, planes * 4, 1, 1, 1)
            total += f1 + f2 + f3
            if i == 0:
                fp, _, _ = _conv_flops_nhwc(h, h, c_in, planes * 4, 1, 1, stride)
                total += fp
            c_in = planes * 4
            h = oh
    total += 2.0 * c_in * n_classes  # fc head
    return 3.0 * total


def _lenet_train_flops_per_example() -> float:
    fwd = (2.0 * 24 * 24 * 20 * 5 * 5 * 1      # conv1
           + 2.0 * 8 * 8 * 50 * 5 * 5 * 20     # conv2
           + 2.0 * 800 * 500                   # dense
           + 2.0 * 500 * 10)                   # out
    return 3.0 * fwd


def _lstm_train_flops_per_example(vocab, hidden, layers, t) -> float:
    """Analytic GravesLSTM stack fwd FLOPs per example; train ≈ 3× fwd."""
    per_step = 0.0
    n_in = vocab
    for _ in range(layers):
        per_step += 2.0 * n_in * 4 * hidden     # input projection
        per_step += 2.0 * hidden * 4 * hidden   # recurrent matmul
        n_in = hidden
    per_step += 2.0 * hidden * vocab            # rnn output layer
    return 3.0 * per_step * t


def _stage_batches(k, batch, shape, n_classes, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(k, batch) + shape).astype(np.float32)
    ys = np.eye(n_classes, dtype=np.float32)[
        rng.integers(0, n_classes, (k, batch))]
    return xs, ys


# NB: np.asarray (device→host transfer) is the completion barrier everywhere
# below; block_until_ready returns early through the axon dev tunnel.


def bench_lenet() -> dict:
    import jax
    from deeplearning4j_tpu.models import lenet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    # bs1024: small-model MFU is dispatch/HBM-bound and scales with
    # batch (512: 3.2%, 1024: 6.9%, 2048: 8.3% measured); k=256 amortizes
    # per-update overhead further (k=32: 0.8-1.0M, k=256: 1.68M ex/s;
    # bf16 measured SLOWER here — layout conversions dominate tiny convs)
    batch, k, rounds = 1024, 256, 4
    net = MultiLayerNetwork(lenet()).init()
    xs, ys = _stage_batches(1, batch, (784,), 10, seed=7)
    x, y = jax.device_put(xs[0]), jax.device_put(ys[0])
    np.asarray(net.fit_repeated(x, y, k))  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(rounds):
        losses = net.fit_repeated(x, y, k)
    np.asarray(losses)
    dt = time.perf_counter() - t0
    steps = rounds * k
    eps = steps * batch / dt
    mfu = eps * _lenet_train_flops_per_example() / _peak_flops_per_sec()
    out = {"examples_per_sec": round(eps, 1), "mfu": round(mfu, 4),
           "step_ms": round(1000 * dt / steps, 3), "batch": batch}
    out.update(_mfu_crosscheck(
        "MultiLayerNetwork.train_repeat",
        _lenet_train_flops_per_example() * batch * k))
    return out


def _make_resnet():
    from deeplearning4j_tpu.models import resnet50
    from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph

    image = int(os.environ.get("BENCH_RESNET_IMAGE", "224"))
    batch = int(os.environ.get("BENCH_RESNET_BATCH", "128"))
    conf = resnet50(height=image, width=image,
                    dtype=os.environ.get("BENCH_RESNET_DTYPE", "mixed_bf16"))
    return ComputationGraph(conf).init(), image, batch


def bench_resnet50() -> dict:
    """ResNet-50 training MFU via the public ComputationGraph.fit_repeated
    API: K optimizer updates on one staged device batch per dispatch, so
    arbitrarily long on-chip runs cost one batch of HBM — isolating train-step
    compute the way a production input pipeline (prefetching while computing)
    would."""
    import jax

    net, image, batch = _make_resnet()
    k = int(os.environ.get("BENCH_RESNET_SCAN", "64"))  # 46.9 vs 47.6 ms at 32
    rounds = 2
    xs, ys = _stage_batches(1, batch, (image, image, 3), 1000, seed=11)
    x = jax.device_put(xs[0])
    y = jax.device_put(ys[0])

    np.asarray(net.fit_repeated([x], [y], k))  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(rounds):
        losses = net.fit_repeated([x], [y], k)
    np.asarray(losses)
    dt = time.perf_counter() - t0

    steps = rounds * k
    eps = steps * batch / dt
    mfu = (eps * _resnet50_train_flops_per_example(image)
           / _peak_flops_per_sec())
    out = {"examples_per_sec": round(eps, 1), "mfu": round(mfu, 4),
           "step_ms": round(1000 * dt / steps, 3), "batch": batch,
           "image": image}
    out.update(_mfu_crosscheck(
        "ComputationGraph.train_repeat",
        _resnet50_train_flops_per_example(image) * batch * k))
    return out


def bench_resnet50_pipeline() -> dict:
    """End-to-end variant: ``net.fit(AsyncDataSetIterator(...))`` over a
    device-staged pool (standing in for a decoded-image cache already moved
    to HBM) — demonstrating the public iterator + fit path adds negligible
    overhead over the synthetic loop.

    Host→device bandwidth is reported separately (``h2d_MBps``): in this
    harness the TPU sits behind a dev tunnel (~tens of MB/s), so timing raw
    per-batch transfers would measure the tunnel, not the framework; on a
    real TPU VM the same transfers ride >10 GB/s DMA and the async prefetch
    overlaps them (AsyncDataSetIterator parity:
    reference ``AsyncDataSetIterator.java:36``)."""
    import jax
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import (
        AsyncDataSetIterator, ExistingDataSetIterator)

    net, image, batch = _make_resnet()
    k = int(os.environ.get("BENCH_RESNET_PIPE_SCAN", "8"))
    blocks = int(os.environ.get("BENCH_RESNET_PIPE_BLOCKS", "4"))

    pool_xs, pool_ys = _stage_batches(4, batch, (image, image, 3), 1000,
                                      seed=13)
    # measure h2d once (one batch), then stage the pool on device
    t0 = time.perf_counter()
    dev0 = jax.device_put(pool_xs[0])
    np.asarray(dev0[0, 0, 0, :1])  # transfer barrier
    h2d_s = time.perf_counter() - t0
    h2d_mbps = pool_xs[0].nbytes / 1e6 / h2d_s
    dev_xs = [dev0] + [jax.device_put(pool_xs[i]) for i in range(1, 4)]
    dev_ys = [jax.device_put(pool_ys[i]) for i in range(4)]

    def batches(n):
        for i in range(n):
            j = i % len(dev_xs)
            yield DataSet(dev_xs[j], dev_ys[j])

    def run(n):
        # the REAL product path: fit(iterator) → per-batch jitted fit_batch,
        # async dispatch overlapping the prefetch thread
        net.fit(AsyncDataSetIterator(ExistingDataSetIterator(batches(n)),
                                     queue_size=2 * k))
        np.asarray(net._score)

    run(k)  # warmup/compile
    t0 = time.perf_counter()
    run(blocks * k)
    dt = time.perf_counter() - t0
    steps = blocks * k
    eps = steps * batch / dt
    mfu = (eps * _resnet50_train_flops_per_example(image)
           / _peak_flops_per_sec())
    return {"examples_per_sec": round(eps, 1), "mfu": round(mfu, 4),
            "step_ms": round(1000 * dt / steps, 3), "batch": batch,
            "image": image, "h2d_MBps": round(h2d_mbps, 1)}


def bench_ingest() -> dict:
    """The fit-vs-synthetic gap (ISSUE 4 acceptance): end-to-end
    ``fit(iterator)`` over HOST numpy batches — exercising the default
    ingest stage (background device_put double-buffering), the bounded
    in-flight window, and lazy scores — against the synthetic
    ``fit_repeated`` on-chip loop for the same model. Reports the ingest
    metrics the run produced (queue depth, h2d MBps, host-gap histogram
    mean) alongside the step times; r4 measured this gap at +5% before
    the async-dispatch loop landed.
    """
    import jax
    from deeplearning4j_tpu.util import metrics as _metrics

    model = os.environ.get(
        "BENCH_INGEST_MODEL",
        "lenet" if os.environ.get("BENCH_SKIP_RESNET") == "1" else "resnet")
    if model == "resnet":
        net, image, batch = _make_resnet()
        shape, n_classes = (image, image, 3), 1000
        wrap = lambda a: [a]
    else:   # lenet: small/CPU-friendly fallback
        from deeplearning4j_tpu.models import lenet
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net, batch = MultiLayerNetwork(lenet()).init(), 256
        shape, n_classes = (784,), 10
        wrap = lambda a: a

    k = int(os.environ.get("BENCH_INGEST_SCAN", "32"))
    blocks = int(os.environ.get("BENCH_INGEST_BLOCKS", "2"))
    xs, ys = _stage_batches(1, batch, shape, n_classes, seed=29)
    x, y = jax.device_put(xs[0]), jax.device_put(ys[0])

    # synthetic ceiling: K fused on-chip updates per dispatch (same K as
    # the warmup — K is a static argnum, a different one would recompile)
    np.asarray(net.fit_repeated(wrap(x), wrap(y), k))  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(blocks):
        losses = net.fit_repeated(wrap(x), wrap(y), k)
    np.asarray(losses)
    synth_ms = 1000 * (time.perf_counter() - t0) / (blocks * k)

    # end-to-end product path: fit() over HOST batches through the
    # default ingest stage (the staging thread pays the h2d, the loop
    # never reads a loss)
    hx, hy = np.asarray(xs[0]), np.asarray(ys[0])

    def batches(n):
        for _ in range(n):
            yield hx, hy

    net.fit(batches(k))                  # warmup (compiles the per-batch step)
    np.asarray(net._score)
    t0 = time.perf_counter()
    net.fit(batches(blocks * k))
    np.asarray(net._score)
    e2e_ms = 1000 * (time.perf_counter() - t0) / (blocks * k)

    reg = _metrics.REGISTRY
    h2d_b = reg.get("ingest_h2d_bytes_total")
    h2d_s = reg.get("ingest_h2d_seconds_total")
    gap_h = reg.get("fit_host_gap_seconds")
    mname = type(net).__name__
    out = {"fit_step_ms": round(e2e_ms, 3),
           "synthetic_step_ms": round(synth_ms, 3),
           "gap_pct": round(100 * (e2e_ms - synth_ms) / synth_ms, 2),
           "batch": batch, "model": model}
    depth = reg.get("ingest_queue_depth")     # absent under DL4JTPU_INGEST=0
    if depth is not None:
        out["queue_depth"] = depth.value(stage="fit")
    if h2d_b is not None and h2d_s is not None:
        secs = h2d_s.value(stage="fit")
        if secs > 0:
            out["h2d_MBps"] = round(h2d_b.value(stage="fit") / 1e6 / secs, 1)
    if gap_h is not None and gap_h.count(model=mname):
        out["host_gap_ms_mean"] = round(
            1000 * gap_h.sum(model=mname) / gap_h.count(model=mname), 3)
    return out


def bench_input_pipeline() -> dict:
    """Records-fed ResNet A/B vs the synthetic device-staged pool
    (ISSUE 14 acceptance): the SAME model and step count trained once
    from sharded record files through the full input pipeline (decode +
    shard/buffer shuffles + the jitted crop/flip/normalize augmentation
    + default ingest staging) and once from an HBM-resident pool (the
    input-cost-free ceiling every prior round used). Reports records/s,
    augment seconds/batch, and the ``fit_host_gap_seconds`` split for
    BOTH runs — the acceptance is the records-fed host gap staying ≤2%
    of step time (the input hides behind the step on its staging
    thread). Payload fields ``input_pipeline_records_per_s`` and
    ``input_host_gap_pct`` ride out of main().

    ``BENCH_SKIP_RESNET=1`` (CPU harness) swaps in ``resnet_tiny`` at
    CIFAR geometry — same DAG shape, so the pipeline/step overlap story
    is exercised end to end without the ImageNet compile cost."""
    import shutil
    import tempfile

    import jax
    from deeplearning4j_tpu.data.pipeline import (Augment, AugmentStage,
                                                  RecordDataSetIterator)
    from deeplearning4j_tpu.data.records import write_shard_set
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.util import ingest as _ingest

    if os.environ.get("BENCH_SKIP_RESNET") == "1":
        from deeplearning4j_tpu.models import resnet_tiny
        from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
        image = int(os.environ.get("BENCH_INPUT_IMAGE", "32"))
        batch = int(os.environ.get("BENCH_INPUT_BATCH", "16"))
        n_classes = 10
        net = ComputationGraph(resnet_tiny(
            height=image, width=image, n_classes=n_classes)).init()
    else:
        net, image, batch = _make_resnet()
        n_classes = 1000
    steps = int(os.environ.get("BENCH_INPUT_STEPS", "24"))
    warm, shards = 4, 4
    mname = type(net).__name__
    eye = np.eye(n_classes, dtype=np.float32)
    tmp = tempfile.mkdtemp(prefix="bench_records_")

    def write(name, n_batches, seed):
        def examples():
            rng = np.random.default_rng(seed)
            for _ in range(n_batches * batch):
                yield {"features": rng.integers(
                            0, 256, (image, image, 3), dtype=np.uint8),
                       "labels": eye[int(rng.integers(0, n_classes))]}
        write_shard_set(tmp, name, examples(), shards)

    # uint8 records + on-device normalize: store bytes, augment in the
    # step's shadow (ImageNet-style mean/std). ONE shared AugmentStage:
    # the warm run must compile the SAME jitted program the timed run
    # dispatches, or its compile wall lands inside the measurement
    aug_stage = AugmentStage(
        Augment(crop_pad=max(1, image // 8), flip=True, scale=1 / 255.0,
                mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
        seed=5, stage_name="bench")

    def records_iter(name):
        return RecordDataSetIterator(
            tmp, name, batch_size=batch, seed=5, shuffle_shards=True,
            shuffle_buffer=2 * batch, augment=aug_stage,
            drop_remainder=True, stage_name="bench")

    gap_h = _ingest.host_gap_histogram()
    aug_c = _ingest.augment_seconds_counter()
    rec_c = _ingest.records_read_counter()

    def gap_state():
        return gap_h.sum(model=mname), gap_h.count(model=mname)

    try:
        t0 = time.perf_counter()
        write("warm", warm, 43)
        write("bench", steps, 47)
        write_s = time.perf_counter() - t0
        net.fit(records_iter("warm"))        # compile augment + train step
        np.asarray(net._score)
        g0, c0 = gap_state()
        a0 = aug_c.value(stage="bench")
        r0 = rec_c.value(stage="bench")
        t0 = time.perf_counter()
        net.fit(records_iter("bench"))
        np.asarray(net._score)
        dt = time.perf_counter() - t0
        g1, c1 = gap_state()
        a1 = aug_c.value(stage="bench")
        r1 = rec_c.value(stage="bench")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    rec_step_ms = 1000 * dt / steps
    rec_gap_ms = 1000 * (g1 - g0) / max(c1 - c0, 1)

    # B: the synthetic ceiling — same step, inputs already in HBM
    rng = np.random.default_rng(53)
    dev_xs = [jax.device_put(rng.normal(
        size=(batch, image, image, 3)).astype(np.float32))
        for _ in range(4)]
    dev_ys = [jax.device_put(eye[rng.integers(0, n_classes, batch)])
              for _ in range(4)]

    def pool(n):
        for i in range(n):
            yield DataSet(dev_xs[i % 4], dev_ys[i % 4])

    net.fit(pool(warm))
    np.asarray(net._score)
    g0, c0 = gap_state()
    t0 = time.perf_counter()
    net.fit(pool(steps))
    np.asarray(net._score)
    sdt = time.perf_counter() - t0
    g1, c1 = gap_state()
    syn_step_ms = 1000 * sdt / steps
    syn_gap_ms = 1000 * (g1 - g0) / max(c1 - c0, 1)

    return {"records_per_s": round(steps * batch / dt, 1),
            "records_read": int(r1 - r0),
            "step_ms_records": round(rec_step_ms, 3),
            "step_ms_synthetic": round(syn_step_ms, 3),
            "step_overhead_pct": round(
                100 * (rec_step_ms - syn_step_ms) / syn_step_ms, 2),
            "host_gap_ms_records": round(rec_gap_ms, 4),
            "host_gap_ms_synthetic": round(syn_gap_ms, 4),
            "gap_pct_records": round(100 * rec_gap_ms / rec_step_ms, 2),
            "gap_pct_synthetic": round(100 * syn_gap_ms / syn_step_ms, 2),
            "augment_ms_per_batch": round(1000 * (a1 - a0) / steps, 3),
            "shard_write_s": round(write_s, 2),
            "batch": batch, "image": image, "steps": steps,
            "shards": shards, "model": mname}


def bench_checkpoint() -> dict:
    """Async-checkpoint overhead (ISSUE 5 acceptance): steady-state
    ``fit(iterator)`` step time with durable checkpointing OFF vs ON
    (single-outstanding background writer, every ``frequency`` steps).
    The commit must never block a step for a full write — the measured
    delta plus the registry's ``checkpoint_write_seconds`` mean proves
    the write cost stayed off the critical path."""
    import shutil
    import tempfile

    from deeplearning4j_tpu.datasets import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.models import lenet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.util import metrics as _metrics
    from deeplearning4j_tpu.util.durable import (AsyncCheckpointWriter,
                                                 CheckpointStore,
                                                 DurableSession)

    batch = int(os.environ.get("BENCH_CKPT_BATCH", "256"))
    steps = int(os.environ.get("BENCH_CKPT_STEPS", "64"))
    frequency = int(os.environ.get("BENCH_CKPT_FREQ", "8"))
    xs, ys = _stage_batches(1, batch, (784,), 10, seed=31)
    hx, hy = np.asarray(xs[0]), np.asarray(ys[0])

    def iterator():
        return ListDataSetIterator([DataSet(hx, hy)] * steps,
                                   batch_size=batch)

    def timed_fit(writer=None):
        net = MultiLayerNetwork(lenet()).init()
        net.fit(iterator())                  # warmup/compile
        np.asarray(net._score)
        session = None
        if writer is not None:
            session = DurableSession(net, writer.store,
                                     frequency=frequency, writer=writer)
        t0 = time.perf_counter()
        net.fit(iterator(), session=session)
        np.asarray(net._score)
        return 1000 * (time.perf_counter() - t0) / steps

    off_ms = timed_fit()

    ckpt_dir = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        writer = AsyncCheckpointWriter(CheckpointStore(ckpt_dir, keep=2))
        on_ms = timed_fit(writer)
        writer.drain()
        writer.close()
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)

    out = {"step_ms_off": round(off_ms, 3), "step_ms_on": round(on_ms, 3),
           "overhead_pct": round(100 * (on_ms - off_ms) / off_ms, 2),
           "frequency": frequency, "steps": steps, "batch": batch}
    hist = _metrics.REGISTRY.get("checkpoint_write_seconds")
    if hist is not None:
        snap = hist.snapshot()["series"]
        if snap and snap[0]["count"]:
            out["write_ms_mean"] = round(
                1000 * snap[0]["sum"] / snap[0]["count"], 2)
    commits = _metrics.REGISTRY.get("checkpoint_commits_total")
    if commits is not None:
        out["commits"] = sum(s["value"] for s in
                             commits.snapshot()["series"])
    return out


def bench_health_stats() -> dict:
    """On-device training-health stats A/B (ISSUE 15 acceptance): the
    SAME model/batch trained with the plain train step vs the
    stats-collecting variant (per-layer norms, update:param ratios,
    activation stats, log-bucket histograms fused into the dispatch).
    Acceptance: ``health_stats_overhead_pct`` ≤ 2% with ZERO added host
    syncs outside listener windows (nothing reads the stats pytree until
    a consumer asks). A second phase attaches a ``HealthListener`` at
    ``frequency`` and pins exactly one sync per window, reporting the
    rules engine's verdicts as the ``training_health`` payload field."""
    import jax
    from deeplearning4j_tpu.models import lenet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.util import health as _health
    from deeplearning4j_tpu.util.ingest import sync_counter

    batch = int(os.environ.get("BENCH_HEALTH_BATCH", "256"))
    steps = int(os.environ.get("BENCH_HEALTH_STEPS", "60"))
    rounds = int(os.environ.get("BENCH_HEALTH_ROUNDS", "3"))
    xs, ys = _stage_batches(1, batch, (784,), 10, seed=37)
    x, y = jax.device_put(xs[0]), jax.device_put(ys[0])

    def arm(stats: bool) -> float:
        """Best-of-rounds steady-state fit_batch step time (ms)."""
        net = MultiLayerNetwork(lenet()).init()
        if stats:
            net.enable_health_stats()
        net.fit_batch(x, y)                   # warmup/compile
        np.asarray(net._score)
        best = None
        for _ in range(rounds):
            t0 = time.perf_counter()
            for _ in range(steps):
                net.fit_batch(x, y)
            np.asarray(net._score)            # completion barrier
            dt = 1000 * (time.perf_counter() - t0) / steps
            best = dt if best is None else min(best, dt)
        return best

    off_ms = arm(False)
    s0 = sync_counter().total()
    on_ms = arm(True)           # listener-free: nothing reads the stats
    syncs_outside_windows = sync_counter().total() - s0

    # listener phase: one sync per frequency window, rules evaluated
    freq = int(os.environ.get("BENCH_HEALTH_FREQ", "10"))
    net = MultiLayerNetwork(lenet()).init()
    listener = _health.HealthListener(frequency=freq, model="bench_lenet")
    net.set_listeners(listener)
    net.fit_batch(x, y)                       # warmup (enables stats)
    np.asarray(net._score)
    s0 = sync_counter().total()
    n = 3 * freq
    it0 = net.iteration_count
    for _ in range(n):
        net.fit_batch(x, y)
    np.asarray(net._score)
    listener_syncs = sync_counter().total() - s0
    windows = sum(1 for i in range(it0 + 1, it0 + n + 1) if i % freq == 0)

    return {
        "step_ms_off": round(off_ms, 3), "step_ms_on": round(on_ms, 3),
        "health_stats_overhead_pct": round(
            100 * (on_ms - off_ms) / off_ms, 2),
        "syncs_outside_windows": syncs_outside_windows,
        "listener_windows": windows, "listener_syncs": listener_syncs,
        "batch": batch, "steps": steps,
        "training_health": listener.engine.last_report,
    }


def bench_lstm() -> dict:
    """Char-RNN GravesLSTM (BASELINE config #3): tokens/s through
    MultiLayerNetwork.fit_repeated on one-hot char sequences."""
    import jax
    from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    vocab = int(os.environ.get("BENCH_LSTM_VOCAB", "80"))
    hidden = int(os.environ.get("BENCH_LSTM_HIDDEN", "512"))
    layers = 2
    t_len = int(os.environ.get("BENCH_LSTM_T", "64"))
    # 512: the largest batch still plausible for char-RNN training;
    # MFU scales with M (128->17.5%, 512->26%, 2048->31.5% measured)
    batch = int(os.environ.get("BENCH_LSTM_BATCH", "512"))
    # k=64 amortizes dispatch further: 2.40M -> 2.96M tokens/s measured
    k, rounds = 64, 2

    conf = char_rnn_lstm(vocab, hidden=hidden, layers=layers,
                         tbptt_length=t_len, dtype="mixed_bf16")
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(17)
    ids = rng.integers(0, vocab, (batch, t_len + 1))
    eye = np.eye(vocab, dtype=np.float32)
    x = jax.device_put(eye[ids[:, :-1]])   # [b, t, vocab]
    y = jax.device_put(eye[ids[:, 1:]])

    np.asarray(net.fit_repeated(x, y, k))  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(rounds):
        losses = net.fit_repeated(x, y, k)
    np.asarray(losses)
    dt = time.perf_counter() - t0
    steps = rounds * k
    eps = steps * batch / dt
    tokens = eps * t_len
    mfu = (eps * _lstm_train_flops_per_example(vocab, hidden, layers, t_len)
           / _peak_flops_per_sec())
    out = {"tokens_per_sec": round(tokens, 1),
           "examples_per_sec": round(eps, 1), "mfu": round(mfu, 4),
           "step_ms": round(1000 * dt / steps, 3), "batch": batch,
           "seq_len": t_len, "hidden": hidden, "vocab": vocab}
    out.update(_mfu_crosscheck(
        "MultiLayerNetwork.train_repeat",
        _lstm_train_flops_per_example(vocab, hidden, layers, t_len)
        * batch * k))
    return out


def bench_word2vec() -> dict:
    """Word2Vec skip-gram negative sampling (BASELINE config #4): training
    pairs/s through nlp.learning.ns_step_scan (the product kernel driving
    SequenceVectors)."""
    import jax
    from deeplearning4j_tpu.nlp import learning

    vocab = int(os.environ.get("BENCH_W2V_VOCAB", "100000"))
    dim = int(os.environ.get("BENCH_W2V_DIM", "128"))
    # 65536 pairs/step, k=128 fused updates: 6.0M pairs/s measured
    # (32k/k64: 5.2M; 131k batches risk stale in-batch gradients)
    b = int(os.environ.get("BENCH_W2V_BATCH", "65536"))
    negs = 5
    k, rounds = 128, 2

    params = learning.init_params(vocab, dim, seed=3, use_neg=True)
    params = jax.device_put(params)
    rng = np.random.default_rng(23)
    centers = jax.device_put(
        rng.integers(0, vocab, (k, b)).astype(np.int32))
    targets = jax.device_put(
        rng.integers(0, vocab, (k, b)).astype(np.int32))
    negss = jax.device_put(
        rng.integers(0, vocab, (k, b, negs)).astype(np.int32))

    lr = np.float32(0.025)
    params, losses = learning.ns_step_scan(
        params, centers, targets, negss, None, None, lr)
    np.asarray(losses)  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(rounds):
        params, losses = learning.ns_step_scan(
            params, centers, targets, negss, None, None, lr)
    np.asarray(losses)
    dt = time.perf_counter() - t0
    pairs = rounds * k * b / dt
    return {"pairs_per_sec": round(pairs, 1), "batch": b, "dim": dim,
            "vocab": vocab, "negatives": negs,
            "step_ms": round(1000 * dt / (rounds * k), 3)}


def bench_flash_attention() -> dict:
    """Long-context attention (beyond the BASELINE set): the Pallas flash
    kernel vs the XLA fused path at bf16 t=8192 — the long-sequence hot op
    behind SelfAttentionLayer / sequence models. See PERF.md."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.attention import dot_product_attention
    from deeplearning4j_tpu.ops.flash_attention import flash_attention

    b, t, h, d = 4, 8192, 8, 128
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.bfloat16)
    f_xla = jax.jit(lambda q, k, v: jnp.sum(
        dot_product_attention(q, k, v, causal=True).astype(jnp.float32)))
    f_flash = jax.jit(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, True).astype(jnp.float32)))

    def _grad(attn):
        def f(q, k, v):
            g = jax.grad(lambda q, k, v: jnp.sum(
                attn(q, k, v).astype(jnp.float32)), argnums=(0, 1, 2))(
                    q, k, v)
            return sum(jnp.sum(x.astype(jnp.float32)) for x in g)
        return jax.jit(f)

    g_xla = _grad(lambda q, k, v: dot_product_attention(q, k, v,
                                                        causal=True))
    g_flash = _grad(lambda q, k, v: flash_attention(q, k, v, True))

    def _t(f, iters=15):
        float(f(q, k, v))
        t0 = time.perf_counter()
        for _ in range(iters):
            s = f(q, k, v)
        float(s)
        return (time.perf_counter() - t0) / iters * 1e3

    prior_flag = os.environ.get("DL4JTPU_FLASH_ATTENTION")
    os.environ["DL4JTPU_FLASH_ATTENTION"] = "0"   # force the XLA route
    try:
        ms_xla = _t(f_xla)
        ms_xla_grad = _t(g_xla, iters=10)
    finally:
        if prior_flag is None:
            os.environ.pop("DL4JTPU_FLASH_ATTENTION", None)
        else:
            os.environ["DL4JTPU_FLASH_ATTENTION"] = prior_flag
    ms_flash = _t(f_flash)
    ms_flash_grad = _t(g_flash, iters=10)
    flops = 4.0 * b * h * t * t * d / 2  # causal
    return {"xla_ms": round(ms_xla, 2), "flash_ms": round(ms_flash, 2),
            "speedup": round(ms_xla / ms_flash, 2),
            "xla_grad_ms": round(ms_xla_grad, 2),
            "flash_grad_ms": round(ms_flash_grad, 2),
            "grad_speedup": round(ms_xla_grad / ms_flash_grad, 2),
            "flash_tflops": round(flops / ms_flash / 1e9, 1),
            "seq_len": t, "dtype": "bfloat16"}


def _transformer_train_flops_per_token(d_model, n_layers, d_ff, vocab,
                                       t) -> float:
    """Analytic train FLOPs per token for the decoder-only LM, stated
    once (the MFU numerator's single source of truth, PERF.md r8):

        3 × [ 2·(L·(4·d² + 2·d·d_ff) + d·V)  +  L·2·(T/2)·d·2 ]

    i.e. train ≈ 3× forward; forward = 2 FLOPs per matmul-parameter MAC
    (Wqkv 3d² + Wo d² + FFN 2·d·d_ff per layer, plus the d·V vocab head —
    the embedding GATHER does no FLOPs, which is the point of the
    integer-id input path), plus the causal attention matmuls (QKᵀ and
    PV: 2 matmuls × 2 FLOPs × T/2 average attended keys × d per layer).
    LayerNorm/softmax/residual vector work is excluded, same convention
    as the ResNet formula above."""
    matmul_params = (n_layers * (4.0 * d_model * d_model
                                 + 2.0 * d_model * d_ff)
                     + d_model * vocab)
    attn = n_layers * 2.0 * (t / 2.0) * d_model * 2.0
    return 3.0 * (2.0 * matmul_params + attn)


def bench_transformer_lm() -> dict:
    """Transformer-LM flagship (ROADMAP item 1): GPT-2-class config —
    d_model 768, 12 layers, 12 heads, T=2048, V=32768 — trained through
    the PUBLIC fit_repeated path on integer token ids (the one-hot
    [b, T, V] construction dies at V≫8; ids are 4 bytes/token), with the
    Pallas flash attention kernel forced on (fwd+bwd; T=2048 sits below
    the auto-route threshold but well inside the kernel's measured-win
    band). Reports MFU from the analytic FLOPs formula above — the
    metric the >40% north star is stated in, reachable here because
    transformer GEMMs (K≈768–3072) sit in this chip's 55–67 TF shape
    band (PERF.md r4 probes), unlike ResNet's conv mix."""
    from deeplearning4j_tpu.models import transformer_lm
    from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph

    V = int(os.environ.get("BENCH_TLM_VOCAB", "32768"))
    T = int(os.environ.get("BENCH_TLM_T", "2048"))
    b = int(os.environ.get("BENCH_TLM_BATCH", "8"))
    d_model = int(os.environ.get("BENCH_TLM_DMODEL", "768"))
    n_layers = int(os.environ.get("BENCH_TLM_LAYERS", "12"))
    n_heads = d_model // 64
    d_ff = 4 * d_model
    k, rounds = int(os.environ.get("BENCH_TLM_SCAN", "8")), 2

    prior = os.environ.get("DL4JTPU_FLASH_ATTENTION")
    os.environ["DL4JTPU_FLASH_ATTENTION"] = "1"
    try:
        net = ComputationGraph(transformer_lm(
            V, n_layers=n_layers, d_model=d_model, n_heads=n_heads,
            d_ff=d_ff, learning_rate=3e-4, dtype="mixed_bf16",
            input_ids=True)).init()
        rng = np.random.default_rng(19)
        ids = rng.integers(0, V, (b, T + 1)).astype(np.int32)
        x, y = ids[:, :-1], ids[:, 1:]
        np.asarray(net.fit_repeated([x], [y], k))  # warmup/compile
        t0 = time.perf_counter()
        for _ in range(rounds):
            losses = net.fit_repeated([x], [y], k)
        np.asarray(losses)
        step_s = (time.perf_counter() - t0) / (rounds * k)
    finally:
        if prior is None:
            os.environ.pop("DL4JTPU_FLASH_ATTENTION", None)
        else:
            os.environ["DL4JTPU_FLASH_ATTENTION"] = prior
    tokens_per_sec = b * T / step_s
    fpt = _transformer_train_flops_per_token(d_model, n_layers, d_ff, V, T)
    mfu = tokens_per_sec * fpt / _peak_flops_per_sec()
    out = {"step_ms": round(step_s * 1e3, 2),
           "tokens_per_sec": round(tokens_per_sec, 1),
           "mfu": round(mfu, 4),
           "model_flops_per_token": round(fpt, 1),
           "batch": b, "seq_len": T, "d_model": d_model,
           "n_layers": n_layers, "n_heads": n_heads, "d_ff": d_ff,
           "vocab": V, "input_mode": "ids", "dtype": "mixed_bf16",
           "attention": "pallas_flash"}
    out.update(_mfu_crosscheck("ComputationGraph.train_repeat",
                               fpt * b * T * k))
    # the measured-MFU column: same step timing, but the NUMERATOR is the
    # compiled program's cost-analysis FLOPs instead of the formula
    if "compiled_flops_per_dispatch" in out:
        out["measured_mfu"] = round(
            out["compiled_flops_per_dispatch"] / (b * T * k)
            * tokens_per_sec / _peak_flops_per_sec(), 4)
    return out


def _bench_prefix_cache(net, baseline_engine, vocab, lanes, page_size,
                        pages_per_seq, block_len) -> dict:
    """Shared-prefix serving A/B + int8 KV-quantization quality/capacity
    (ISSUE 19), appended to the decode payload:

    - **prefix_hit_ttft_ms** — TTFT for requests whose WHOLE prompt is
      resident in the prefix index (a warm 2-page system prompt): the
      acceptance claim is that a full hit skips prefill entirely and
      pays roughly one decode-step dispatch. Partial hits (shared
      prefix + private tail) and the same Poisson schedule replayed on
      the warm prefix-off engine give the contrast rows.
    - **kv_prefix_hit_rate** — covered prompt tokens / total prompt
      tokens over the measured schedule (plus the admission-outcome
      counts from ``kv_prefix_hits_total``).
    - **int8_logit_max_err** — max |Δ log p| of the int8 paged forward
      vs the dense float oracle (``oracle_stream_probs``) over a
      4-page sequence, plus the greedy-divergence rate: the measured
      quality bound PERF.md records for the quantized arena.
    - **concurrent_lanes_at_fixed_arena** — lanes a fixed arena byte
      budget sustains at fp vs int8 pools (int8 codes + per-(page,
      head) scales ≈ ¼ the bytes → ~4× pages), cross-checked by
      actually running the int8 engine at the computed lane count and
      recording the peak concurrently-active lanes.
    """
    from deeplearning4j_tpu.models.transformer import (
        attention_vertices, oracle_stream_probs, paged_decode_forward)
    from deeplearning4j_tpu.serving.decode import (DecodeScheduler,
                                                   PagedDecodeEngine)
    from deeplearning4j_tpu.serving.kv_cache import PagedKVArena
    from deeplearning4j_tpu.util.metrics import MetricsRegistry

    out = {}
    ps = page_size
    rng = np.random.default_rng(53)
    sys_prompt = rng.integers(0, vocab, 2 * ps).astype(np.int32)
    tails = rng.integers(0, vocab, (12, ps // 2)).astype(np.int32)
    # 11 exact repeats of the system prompt (full hits once seeded) +
    # 12 shared-prefix-plus-private-tail prompts (partial hits)
    schedule = [sys_prompt] * 11 + [np.concatenate([sys_prompt, t])
                                    for t in tails]
    order = rng.permutation(len(schedule))
    arrivals = np.cumsum(rng.exponential(0.002, len(schedule)))
    max_new = 8

    def poisson(sched):
        reqs = [None] * len(schedule)
        t0 = time.perf_counter()
        for i, k in enumerate(order):
            dt = arrivals[i] - (time.perf_counter() - t0)
            if dt > 0:
                time.sleep(dt)
            reqs[k] = sched.submit(schedule[k], max_new)
        for r in reqs:
            r.wait(600)
        return reqs

    reg = MetricsRegistry()
    eng = PagedDecodeEngine(net, max_batch=lanes, page_size=ps,
                            pages_per_seq=pages_per_seq, prefill_chunk=ps,
                            block_len=block_len, prefix_cache=True,
                            registry=reg)
    eng.warmup()
    sched = DecodeScheduler(eng, registry=reg, max_queue=64,
                            request_timeout_s=600.0)
    # seed the index (the measured schedule runs against a warm cache)
    seed = sched.submit(sys_prompt, max_new)
    seed.wait(600)
    reqs = poisson(sched)
    sched.stop()

    # the same schedule on the WARM prefix-off fused engine — the
    # prefill-every-time TTFT the hit rows are read against
    base_sched = DecodeScheduler(baseline_engine, max_queue=64,
                                 request_timeout_s=600.0)
    base_reqs = poisson(base_sched)
    base_sched.stop()
    for r, b in zip(reqs, base_reqs):
        assert r.tokens == b.tokens, \
            "prefix-cache greedy output diverged from the prefill path"

    def p50(ttfts):
        s = sorted(ttfts)
        return round(1000 * s[len(s) // 2], 3) if s else None

    full = [r for r in reqs
            if r.prefix_covered_tokens >= len(r.prompt)]
    partial = [r for r in reqs
               if 0 < r.prefix_covered_tokens < len(r.prompt)]
    hits = reg.get("kv_prefix_hits_total")
    out["prefix_hit_ttft_ms"] = p50(
        [r.t_first_token - r.t_submit for r in full])
    out["prefix_partial_ttft_ms"] = p50(
        [r.t_first_token - r.t_submit for r in partial])
    out["prefill_ttft_ms"] = p50(
        [r.t_first_token - r.t_submit for r in base_reqs])
    out["kv_prefix_hit_rate"] = round(
        sum(r.prefix_covered_tokens for r in reqs)
        / sum(len(r.prompt) for r in reqs), 4)
    out["kv_prefix_hits"] = {
        k: int(hits.value(result=k)) for k in ("full", "partial", "miss")}
    out["kv_prefix_cow_detaches"] = int(
        reg.get("kv_pages_cow_total").value())

    # ---- int8 quality bound vs the dense float oracle ----------------
    dims = {}
    for name in attention_vertices(net):
        layer = net.conf.vertices[name].layer
        dims[name] = (layer.n_heads, layer.n_in // layer.n_heads)
    t = 4 * ps
    seq = rng.integers(0, vocab, t).astype(np.int32)
    oracle = oracle_stream_probs(net, seq)                  # [t, V]
    q8 = PagedKVArena(dims, num_pages=pages_per_seq, page_size=ps,
                      kv_dtype="int8", with_allocator=False)
    probs, _, _ = paged_decode_forward(
        net, net.params, q8.k_pools, q8.v_pools, seq[None],
        np.arange(pages_per_seq, dtype=np.int32)[None],
        np.arange(t, dtype=np.int32)[None], np.zeros(1, np.int32))
    probs = np.asarray(probs, np.float64)[0]
    out["int8_logit_max_err"] = round(float(np.max(np.abs(
        np.log(np.maximum(probs, 1e-12))
        - np.log(np.maximum(oracle, 1e-12))))), 5)
    out["int8_greedy_divergence"] = round(float(np.mean(
        np.argmax(probs, axis=-1) != np.argmax(oracle, axis=-1))), 4)

    # ---- lane capacity at fixed arena bytes --------------------------
    per_fp = PagedKVArena(dims, num_pages=1, page_size=ps,
                          with_allocator=False).nbytes()
    per_q8 = PagedKVArena(dims, num_pages=1, page_size=ps,
                          kv_dtype="int8", with_allocator=False).nbytes()
    arena_bytes = lanes * pages_per_seq * per_fp
    q8_pages = int(arena_bytes // per_q8)
    q8_lanes = q8_pages // pages_per_seq
    qreg = MetricsRegistry()
    qeng = PagedDecodeEngine(net, max_batch=q8_lanes, page_size=ps,
                             pages_per_seq=pages_per_seq,
                             num_pages=q8_pages, prefill_chunk=ps,
                             block_len=block_len, kv_dtype="int8",
                             registry=qreg)
    qsched = DecodeScheduler(qeng, registry=qreg,
                             max_queue=q8_lanes + 8,
                             request_timeout_s=600.0)
    qprompts = rng.integers(0, vocab, (q8_lanes, ps)).astype(np.int32)
    qreqs = [qsched.submit(p, 24) for p in qprompts]
    peak = 0
    while not all(r.done for r in qreqs):
        peak = max(peak, qsched.active_count())
        time.sleep(0.005)
    qsched.stop()
    out["concurrent_lanes_at_fixed_arena"] = {
        "arena_mib": round(arena_bytes / 2 ** 20, 2),
        "fp_lanes": lanes,
        "int8_lanes": q8_lanes,
        "int8_sustained_active_lanes": peak,
        "capacity_ratio": round(q8_lanes / lanes, 2),
    }
    return out


def bench_decode() -> dict:
    """Decode-serving A/B under one OPEN-LOOP Poisson arrival schedule
    (ISSUE 9 + ISSUE 11 acceptance): sustained tokens/s plus p50/p99
    TTFT and time-per-output-token for FOUR decode-step shapes over the
    same model, same greedy sampling, same arrivals:

      A. **fused** — the headline: continuous batching with the N-step
         fused device loop (``block_len``; one dispatch, one host sync
         per block) — the `serving_decode_tokens_per_s` secondary
         metric cites THIS path;
      B. **ticked** — the PR-6 continuous-batching baseline (block_len=1,
         one host round-trip per token): the fused path must be no
         worse on the CPU harness;
      C. **speculative** — draft/verify blocks with the target model
         drafting for itself (the acceptance-rate UPPER BOUND: greedy
         target-as-draft accepts every token, so this row measures the
         spec machinery's ceiling and its two-dispatch overhead; a
         trained 2-layer draft's real rate lands with the device-day
         payload);
      D. **wave oracle** — the dense-cache wave-batched floor carried
         since ISSUE 9 (`speedup_vs_wave` trajectory).

    The acceptance numbers are RELATIVE plus the sync-count gauge
    (`decode_host_syncs_per_token` ≤ 1/block_len for the fused path) —
    on the CPU harness the absolute tokens/s measures the host, not the
    chip; TPU absolutes land via this same payload on a device day.
    Decode metrics (occupancy, pages, retire reasons, the
    `decode_host_tick_seconds` split) ride the process registry — which
    the FUSED run owns — into the BENCH payload.
    """
    import warnings

    from deeplearning4j_tpu.models import transformer_lm
    from deeplearning4j_tpu.models.transformer import sample_token
    from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
    from deeplearning4j_tpu.serving.decode import (DecodeScheduler,
                                                   PagedDecodeEngine)
    from deeplearning4j_tpu.util import metrics as _metrics
    from deeplearning4j_tpu.util.metrics import MetricsRegistry

    vocab = int(os.environ.get("BENCH_DECODE_VOCAB", "256"))
    d_model = int(os.environ.get("BENCH_DECODE_DMODEL", "64"))
    n_layers = int(os.environ.get("BENCH_DECODE_LAYERS", "2"))
    lanes = int(os.environ.get("BENCH_DECODE_LANES", "8"))
    n_req = int(os.environ.get("BENCH_DECODE_REQS", "96"))
    block_len = int(os.environ.get("BENCH_DECODE_BLOCK", "8"))
    draft_k = int(os.environ.get("BENCH_DECODE_DRAFT_K", "4"))
    page_size, pages_per_seq = 16, 8
    window = page_size * pages_per_seq            # 128
    lp = 16                                       # prompt length
    iat_s = float(os.environ.get("BENCH_DECODE_IAT_MS", "2")) / 1000.0

    conf = transformer_lm(vocab, n_layers=n_layers, d_model=d_model,
                          n_heads=d_model // 16, d_ff=4 * d_model,
                          input_ids=True, max_cache_t=window)
    net = ComputationGraph(conf).init()

    rng = np.random.default_rng(37)
    prompts = rng.integers(0, vocab, (n_req, lp)).astype(np.int32)
    # mixed output lengths: the head of short chats + the long tail that
    # strands a wave's lanes (mean 25, wave max ≈ 96 → a wave burns
    # ~3/4 of its step-slots on finished lanes)
    lens = rng.choice([4, 8, 16, 96], size=n_req,
                      p=[0.35, 0.35, 0.1, 0.2])
    arrivals = np.cumsum(rng.exponential(iat_s, n_req))

    def poisson_run(registry, tracer=None, engine=None, **engine_kw):
        """One continuous-batching run over the shared schedule; every
        mode gets its own registry so sync/token accounting is clean.
        ``engine`` reuses an already-warm engine (same compiled ladder)
        for an A/B where only the scheduler config differs."""
        if engine is None:
            engine = PagedDecodeEngine(net, max_batch=lanes,
                                       page_size=page_size,
                                       pages_per_seq=pages_per_seq,
                                       prefill_chunk=lp,
                                       registry=registry, **engine_kw)
            engine.warmup()             # compile the whole trace ladder
        sched = DecodeScheduler(engine, registry=registry,
                                max_queue=n_req + 8,
                                request_timeout_s=600.0, tracer=tracer)
        t0 = time.perf_counter()
        reqs = []
        for i in range(n_req):
            dt = arrivals[i] - (time.perf_counter() - t0)
            if dt > 0:
                time.sleep(dt)
            reqs.append(sched.submit(prompts[i], int(lens[i])))
        for r in reqs:
            r.wait(600)
        wall = time.perf_counter() - t0
        sched.stop()
        tokens = sum(len(r.tokens) for r in reqs)
        ttfts = sorted(r.t_first_token - r.t_submit for r in reqs)
        tpots = [(r.t_done - r.t_first_token) / (len(r.tokens) - 1)
                 for r in reqs if len(r.tokens) > 1]
        syncs = registry.get("decode_host_syncs_total").value()
        return {"tokens_per_s": tokens / wall,
                "tokens": tokens,
                "ttft_p50_ms": 1000 * ttfts[len(ttfts) // 2],
                "ttft_p99_ms": 1000 * ttfts[int(0.99 * (len(ttfts) - 1))],
                "tpot_ms": 1000 * float(np.mean(tpots)),
                "host_syncs_per_token": syncs / max(tokens, 1),
                "registry": registry,
                "engine": engine,
                "reqs": reqs,
                "outputs": [r.tokens for r in reqs]}

    # ---- A: FUSED continuous batching (owns the process registry) ---
    fused = poisson_run(_metrics.REGISTRY, block_len=block_len)
    # HEADLINE-run registry snapshots, captured before the A/B reruns
    # below keep writing into the same process registry: totals (goodput
    # split, evicted pages) and the tick-split means must describe the
    # headline fused run alone, not 6 stacked schedules
    _goodput = _metrics.REGISTRY.get("decode_goodput_tokens_total")
    goodput_met = int(_goodput.value(slo="met"))
    goodput_missed = int(_goodput.value(slo="missed"))
    _evicted = _metrics.REGISTRY.get("kv_pages_evicted_total")
    kv_evicted_headline = (int(_evicted.value())
                           if _evicted is not None else None)
    _occ = _metrics.REGISTRY.get("decode_batch_occupancy")
    occ_headline = ((_occ.sum(), _occ.count())
                    if _occ is not None else (0.0, 0))
    _tick = _metrics.REGISTRY.get("decode_host_tick_seconds")
    tick_headline = (_tick.snapshot()["series"]
                     if _tick is not None else [])
    # ---- A': same engine + schedule with per-request tracing ON — the
    # measured cost of the request-timeline instrumentation (PERF
    # acceptance: ≤1% on tokens/s) and the source of the sample
    # timeline + TTFT decomposition in this payload. One Poisson run is
    # ~0.3s of wall, so single-run tokens/s jitters by several percent;
    # the A/B compares BEST-of-3 per side on the shared warm engine
    from deeplearning4j_tpu.util import timeline as _timeline
    from deeplearning4j_tpu.util.tracing import Tracer
    fused_best = fused["tokens_per_s"]
    for _ in range(2):
        rep = poisson_run(_metrics.REGISTRY, engine=fused["engine"])
        assert rep["outputs"] == fused["outputs"]
        fused_best = max(fused_best, rep["tokens_per_s"])
    tracer = Tracer(max_spans=100000)
    traced, traced_best = None, 0.0
    for _ in range(3):
        t = poisson_run(_metrics.REGISTRY, tracer=tracer,
                        engine=fused["engine"])
        assert t["outputs"] == fused["outputs"]
        if t["tokens_per_s"] > traced_best:
            traced_best, traced = t["tokens_per_s"], t
    # ---- B: the PR-6 host-ticked baseline ----------------------------
    ticked = poisson_run(MetricsRegistry())
    # ---- C: speculative (target-as-draft acceptance ceiling) ---------
    spec = poisson_run(MetricsRegistry(), draft_net=net, draft_k=draft_k)
    assert fused["outputs"] == ticked["outputs"] == spec["outputs"], \
        "greedy decode diverged between step shapes"
    spec_reg = spec["registry"]
    acc = spec_reg.get("decode_draft_tokens_total").value(result="accepted")
    rej = spec_reg.get("decode_draft_tokens_total").value(result="rejected")
    cont, cont_tokens = fused, fused["tokens"]

    # ---- B: wave-batched oracle (dense cache, padded waves) ----------
    def wave_step(x):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")     # window warnings
            return np.asarray(net.rnn_time_step(x))

    # warmup both wave shapes
    net.rnn_clear_previous_state()
    wave_step(np.zeros((lanes, lp, 1), np.int32))
    wave_step(np.zeros((lanes, 1, 1), np.int32))

    t0 = time.perf_counter()
    idx, wave_tokens = 0, 0
    wave_ttfts = []
    while idx < n_req:
        now = time.perf_counter() - t0
        if arrivals[idx] > now:
            time.sleep(arrivals[idx] - now)
            now = arrivals[idx]
        take = [idx]
        while (len(take) < lanes and idx + len(take) < n_req
               and arrivals[idx + len(take)] <= now):
            take.append(idx + len(take))
        b = len(take)
        x = np.zeros((lanes, lp, 1), np.int32)    # padded to fixed lanes
        x[:b, :, 0] = prompts[take]
        net.rnn_clear_previous_state()
        probs = wave_step(x)[:, -1]
        t_first = time.perf_counter() - t0
        wave_ttfts += [t_first - arrivals[j] for j in take]
        need = lens[take]
        toks = np.zeros(lanes, np.int32)
        produced = np.zeros(b, np.int64)
        for i in range(b):
            toks[i] = sample_token(probs[i])
            produced[i] = 1
        # the wave holds EVERY lane until its longest member finishes
        for _ in range(int(need.max()) - 1):
            probs = wave_step(toks[:, None, None])[:, 0]
            for i in range(b):
                if produced[i] < need[i]:
                    toks[i] = sample_token(probs[i])
                    produced[i] += 1
        wave_tokens += int(produced.sum())
        idx += b
    wave_wall = time.perf_counter() - t0
    wave_tps = wave_tokens / wave_wall
    wave_ttfts.sort()

    assert cont_tokens == wave_tokens == int(lens.sum())
    out = {"continuous_tokens_per_s": round(cont["tokens_per_s"], 1),
           "ticked_tokens_per_s": round(ticked["tokens_per_s"], 1),
           "spec_tokens_per_s": round(spec["tokens_per_s"], 1),
           "wave_tokens_per_s": round(wave_tps, 1),
           "speedup_vs_wave": round(cont["tokens_per_s"] / wave_tps, 2),
           "speedup_vs_ticked": round(
               cont["tokens_per_s"] / ticked["tokens_per_s"], 3),
           "block_len": block_len, "draft_k": draft_k,
           "decode_host_syncs_per_token": round(
               cont["host_syncs_per_token"], 4),
           "ticked_host_syncs_per_token": round(
               ticked["host_syncs_per_token"], 4),
           "spec_host_syncs_per_token": round(
               spec["host_syncs_per_token"], 4),
           "draft_acceptance_rate": round(acc / max(acc + rej, 1), 4),
           "spec_draft": "target-as-draft (acceptance upper bound)",
           "ttft_p50_ms": round(cont["ttft_p50_ms"], 2),
           "ttft_p99_ms": round(cont["ttft_p99_ms"], 2),
           "ticked_tpot_ms": round(ticked["tpot_ms"], 3),
           "spec_tpot_ms": round(spec["tpot_ms"], 3),
           "wave_ttft_p50_ms": round(
               1000 * wave_ttfts[len(wave_ttfts) // 2], 2),
           "wave_ttft_p99_ms": round(
               1000 * wave_ttfts[int(0.99 * (len(wave_ttfts) - 1))], 2),
           "tpot_ms": round(cont["tpot_ms"], 3),
           "requests": n_req, "lanes": lanes, "window": window,
           "page_size": page_size, "prompt_len": lp,
           "output_lens": "4/8/16/96 @ .35/.35/.1/.2",
           "total_tokens": cont_tokens,
           "arrival_iat_ms": round(1000 * iat_s, 1)}
    occ_sum, occ_count = occ_headline
    if occ_count:
        out["mean_decode_occupancy"] = round(occ_sum / occ_count, 2)
    if kv_evicted_headline is not None:
        out["kv_pages_evicted"] = kv_evicted_headline
    # the measured host-tick split (ISSUE 11 satellite): mean seconds per
    # component across the HEADLINE fused run's scheduler ticks (the
    # snapshot predates the A/B reruns)
    for s in tick_headline:
        if s["count"]:
            out[f"tick_{s['labels']['component']}_mean_ms"] = round(
                1000 * s["sum"] / s["count"], 4)
    # ---- request-timeline observability (ISSUE 13) -------------------
    # goodput next to the throughput row: served tokens by SLO outcome
    out["goodput_tokens_met"] = goodput_met
    out["goodput_tokens_missed"] = goodput_missed
    # measured tracing cost: same engine, same schedule, spans on vs
    # off, best-of-3 each side
    out["traced_tokens_per_s"] = round(traced_best, 1)
    out["tracing_overhead_pct"] = round(
        100.0 * (1.0 - traced_best / fused_best), 2)
    # the TTFT decomposition must SUM to the measured TTFT (acceptance:
    # within 5%); report the worst request so regressions are visible
    errs = []
    for r in traced["reqs"]:
        if r.ttft_breakdown and r.t_first_token is not None:
            ttft = r.t_first_token - r.t_submit
            if ttft > 0:
                errs.append(
                    abs(sum(r.ttft_breakdown.values()) - ttft) / ttft)
    if errs:
        out["ttft_decomposition_max_err_pct"] = round(
            100.0 * max(errs), 4)
        mean_bd = {k: 0.0 for k in
                   ("queue_wait", "prefill", "compile", "dispatch")}
        n_bd = 0
        for r in traced["reqs"]:
            if r.ttft_breakdown:
                n_bd += 1
                for k, v in r.ttft_breakdown.items():
                    mean_bd[k] += v
        out["ttft_breakdown_mean_ms"] = {
            k: round(1000 * v / max(n_bd, 1), 3)
            for k, v in mean_bd.items()}
    # one fully-rendered request timeline (the longest request) as the
    # payload's worked example of the span tree
    timelines = _timeline.request_timelines(tracer)
    if timelines:
        sample = max(timelines,
                     key=lambda t: t["attributes"].get("tokens", 0))
        out["sample_request_timeline"] = json.loads(
            json.dumps(sample, default=repr))
    # ---- prefix caching + int8 KV quantization (ISSUE 19) ------------
    out.update(_bench_prefix_cache(net, fused["engine"], vocab, lanes,
                                   page_size, pages_per_seq, block_len))
    return out


def bench_fleet() -> dict:
    """Serving-fleet tier (ISSUE 20): aggregate decode throughput at 1
    vs 4 routed replicas, plus a rolling ``set_model`` across the
    4-replica fleet under light load with zero shed increase.

    Honest-measurement note: this harness has ONE CPU core, so raw
    engine throughput cannot scale with replica count. Per-dispatch
    DEVICE time is therefore simulated — a FaultPlan hook on the
    ``serving.decode_step`` seam sleeps ``SIM_STEP_S`` inside every
    engine dispatch (sleeps release the GIL, so replica engines overlap
    exactly the way independent accelerators would, while the tiny real
    model keeps the host path honest). What the scaling number measures
    is the FLEET tier itself: router pick quality, HTTP proxying,
    heartbeat/capacity staleness, and scheduler admission — the real
    end-to-end path a multi-host fleet exercises, minus the chips."""
    import tempfile
    import threading
    import urllib.request

    from deeplearning4j_tpu.models import transformer_lm
    from deeplearning4j_tpu.nn.graph_runtime import ComputationGraph
    from deeplearning4j_tpu.parallel.elastic import \
        InMemoryCoordinationStore
    from deeplearning4j_tpu.serving import (FleetRouter, InferenceServer,
                                            ReplicaAgent)
    from deeplearning4j_tpu.util import faults
    from deeplearning4j_tpu.util.serialization import save_model

    VOCAB, WINDOW = 32, 32
    SIM_STEP_S = 0.05           # simulated device time per dispatch
    MAX_NEW = 16
    TIMEOUT_S = 120.0

    def _net(seed=7):
        conf = transformer_lm(VOCAB, n_layers=1, d_model=32, n_heads=2,
                              d_ff=64, seed=seed, input_ids=True,
                              max_cache_t=WINDOW)
        return ComputationGraph(conf).init()

    def _post(port, payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=TIMEOUT_S + 10) as r:
            return json.loads(r.read())

    def build_fleet(n):
        store = InMemoryCoordinationStore()
        servers = [InferenceServer(
            _net(), port=0,
            decode={"max_batch": 2, "page_size": 8, "pages_per_seq": 4,
                    "prefill_chunk": 8, "request_timeout_s": TIMEOUT_S})
            for _ in range(n)]
        agents = [ReplicaAgent(s, store, replica=f"r{i}",
                               lease_s=2.0).start()
                  for i, s in enumerate(servers)]
        router = FleetRouter(store, lease_s=2.0,
                             request_timeout_s=TIMEOUT_S,
                             attempt_timeout_s=TIMEOUT_S)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if router._health()["ready"] == n:
                break
            time.sleep(0.05)
        return store, servers, agents, router

    def teardown(servers, agents, router):
        router.stop()
        for a in agents:
            a.stop(deregister=False)
        for s in servers:
            s.stop(drain=False)

    def measure(router, n_requests, concurrency):
        """Closed-loop: `concurrency` clients drain a shared request
        counter back-to-back; tokens/s over the whole drain."""
        it = iter(range(n_requests))
        lock = threading.Lock()
        done = {"tokens": 0, "errors": 0}

        def worker():
            while True:
                with lock:
                    i = next(it, None)
                if i is None:
                    return
                try:
                    body = _post(router.port,
                                 {"prompt_ids": [1 + i % 6] * 6,
                                  "max_new_tokens": MAX_NEW,
                                  "timeout_s": TIMEOUT_S})
                    with lock:
                        done["tokens"] += len(body["tokens"])
                except Exception:
                    with lock:
                        done["errors"] += 1
        threads = [threading.Thread(target=worker)
                   for _ in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        return done["tokens"] / wall, done["errors"]

    out = {"sim_step_s": SIM_STEP_S, "max_new_tokens": MAX_NEW}
    plan = faults.FaultPlan()
    plan.always("serving.decode_step",
                exc=lambda payload: time.sleep(SIM_STEP_S))

    # ---- scaling: same closed-loop offered load per replica ----------
    for n in (1, 4):
        store, servers, agents, router = build_fleet(n)
        try:
            plan.install()
            try:
                tps, errors = measure(router, n_requests=24 * n,
                                      concurrency=6 * n)
            finally:
                plan.uninstall()
            out[f"tokens_per_s_{n}r"] = round(tps, 1)
            out[f"errors_{n}r"] = errors
            if n == 4:
                reqs = router.registry.get("fleet_requests_total")
                out["router_ok"] = int(reqs.value(outcome="ok"))
                out["failovers"] = int(router.registry.get(
                    "fleet_failovers_total").total())
                # ---- rolling deploy across the 4 replicas under light
                # load (no sim sleeps: swap_net re-warms in the fence
                # and the acceptance is zero shed, not speed)
                shed = router.registry.get("serving_shed_total")
                shed_before = shed.value(reason="no_replica")
                with tempfile.TemporaryDirectory() as d:
                    path = os.path.join(d, "next.zip")
                    save_model(_net(seed=11), path)
                    stop = threading.Event()
                    codes = []

                    def light_load():
                        i = 0
                        while not stop.is_set():
                            i += 1
                            try:
                                _post(router.port,
                                      {"prompt_ids": [1, 2, 3],
                                       "max_new_tokens": 2,
                                       "idempotency_key": f"roll-{i}"})
                                codes.append(200)
                            except Exception:
                                codes.append(-1)
                            time.sleep(0.05)
                    loader = threading.Thread(target=light_load)
                    loader.start()
                    t0 = time.perf_counter()
                    try:
                        rolled = router.rolling_set_model(
                            path, ready_timeout_s=180)
                    finally:
                        stop.set()
                        loader.join(timeout=60)
                    out["rolling_deploy"] = {
                        "replicas": len(rolled),
                        "all_ok": all(r["ok"] for r in rolled),
                        "seconds": round(time.perf_counter() - t0, 2),
                        "requests_during_roll": len(codes),
                        "request_failures": sum(c != 200 for c in codes),
                        "shed_increase": shed.value(reason="no_replica")
                                         - shed_before,
                    }
        finally:
            teardown(servers, agents, router)
    out["fleet_scaling_x"] = round(
        out["tokens_per_s_4r"] / max(out["tokens_per_s_1r"], 1e-9), 2)
    return out


def main() -> None:
    import jax
    device = str(jax.devices()[0].device_kind)
    out = {"device": device}

    lenet_res = _run_config(out, "lenet", bench_lenet)
    resnet_res = None
    if os.environ.get("BENCH_SKIP_RESNET") != "1":
        resnet_res = _run_config(out, "resnet50", bench_resnet50)
        if resnet_res is not None:
            _run_config(out, "resnet50_pipeline", bench_resnet50_pipeline)
    _run_config(out, "ingest", bench_ingest)
    input_res = _run_config(out, "input_pipeline", bench_input_pipeline)
    _run_config(out, "checkpoint", bench_checkpoint)
    health_res = _run_config(out, "health_stats", bench_health_stats)
    _run_config(out, "lstm", bench_lstm)
    _run_config(out, "word2vec", bench_word2vec)
    _run_config(out, "flash_attention", bench_flash_attention)
    tlm_res = _run_config(out, "transformer_lm", bench_transformer_lm)
    decode_res = _run_config(out, "decode", bench_decode)
    fleet_res = _run_config(out, "fleet", bench_fleet)

    # snapshot the process-default metrics registry into the payload so
    # the perf trajectory carries whatever the run recorded (retry
    # counters, batch-size + latency histograms from any instrumented
    # path that defaulted to REGISTRY)
    try:
        from deeplearning4j_tpu.util import metrics as _metrics
        snap = _metrics.REGISTRY.snapshot()
        if snap:
            out["metrics"] = snap
    except Exception:
        pass    # metrics must never erase a round's evidence

    # compile-cost summary + measured-vs-analytic verdict: the compile
    # histogram details ride out["metrics"]["xla_compile_seconds"]; here
    # is the one-line version a human (or the round driver) reads first
    try:
        from deeplearning4j_tpu.util import metrics as _metrics
        hist = _metrics.REGISTRY.get("xla_compile_seconds")
        if hist is not None:
            series = hist.snapshot()["series"]
            out["xla_compile_summary"] = {
                "compiles": int(sum(s["count"] for s in series)),
                "total_seconds": round(sum(s["sum"] for s in series), 2),
            }
        deviations = {
            name: res["flops_deviation_pct"]
            for name, res in out.items()
            if isinstance(res, dict) and "flops_deviation_pct" in res}
        if deviations:
            worst = max(deviations.values(), key=abs)
            out["mfu_crosscheck"] = {
                "deviation_pct_by_config": deviations,
                "worst_deviation_pct": worst,
                "exceeds_warn": abs(worst) > MFU_DEVIATION_WARN_PCT,
            }
    except Exception:
        pass

    # decode-serving row: sustained continuous-batched tokens/s under
    # Poisson load — since ISSUE 11 the headline cites the FUSED
    # multi-token path (block_len decode steps per dispatch), with the
    # PR-6 ticked path and the speculative path as A/B columns;
    # vs_baseline stays the ratio over the wave-batched oracle divided
    # by the 2x acceptance target (the absolute tokens/s measures the
    # host on the CPU harness — the RELATIVE numbers are the acceptance
    # criteria; TPU absolutes land via this same field)
    if decode_res is not None and "continuous_tokens_per_s" in decode_res:
        out["serving_decode_tokens_per_s"] = {
            "metric": "serving_decode_tokens_per_s",
            "value": decode_res["continuous_tokens_per_s"],
            "unit": "tokens/s",
            "path": "fused",
            "block_len": decode_res.get("block_len"),
            "vs_baseline": round(decode_res["speedup_vs_wave"] / 2.0, 4),
            "speedup_vs_wave": decode_res["speedup_vs_wave"],
            "speedup_vs_ticked": decode_res.get("speedup_vs_ticked"),
            "decode_host_syncs_per_token": decode_res.get(
                "decode_host_syncs_per_token"),
            "draft_acceptance_rate": decode_res.get(
                "draft_acceptance_rate"),
            "ttft_p50_ms": decode_res["ttft_p50_ms"],
            "ttft_p99_ms": decode_res["ttft_p99_ms"],
            "tpot_ms": decode_res["tpot_ms"],
        }

    # fleet-scaling row (ISSUE 20): aggregate routed decode throughput
    # at 4 replicas over 1 (target >= 3.2x — fleet-tier overhead bounded
    # at <=20% of linear), plus the rolling-deploy zero-shed evidence;
    # device time is simulated per-dispatch on this 1-core harness (see
    # bench_fleet docstring), so the ratio isolates the fleet tier
    if fleet_res is not None and "fleet_scaling_x" in fleet_res:
        out["fleet_decode_scaling"] = {
            "metric": "fleet_decode_scaling",
            "value": fleet_res["fleet_scaling_x"],
            "unit": "x_at_4_replicas",
            "vs_baseline": round(fleet_res["fleet_scaling_x"] / 3.2, 4),
            "tokens_per_s_1r": fleet_res["tokens_per_s_1r"],
            "tokens_per_s_4r": fleet_res["tokens_per_s_4r"],
            "failovers": fleet_res.get("failovers"),
            "rolling_deploy": fleet_res.get("rolling_deploy"),
        }

    # input-pipeline row (ISSUE 14): records/s through the full
    # records → decode → shuffle → jit-augment → stage() → fit path,
    # with the host-gap split proving the input hides behind the step
    # (acceptance: records-fed gap ≤ 2% of step time, measured by the
    # existing fit_host_gap_seconds gauge)
    if input_res is not None and "records_per_s" in input_res:
        out["input_pipeline_records_per_s"] = {
            "metric": "input_pipeline_records_per_s",
            "value": input_res["records_per_s"],
            "unit": "records/s",
            "input_host_gap_pct": input_res["gap_pct_records"],
            "synthetic_host_gap_pct": input_res["gap_pct_synthetic"],
            "step_overhead_pct": input_res["step_overhead_pct"],
            "augment_ms_per_batch": input_res["augment_ms_per_batch"],
        }
        out["input_host_gap_pct"] = input_res["gap_pct_records"]

    # training-health telemetry row (ISSUE 15): stats-on-vs-off overhead
    # (acceptance ≤2%, same bar family as tracing's ≤1%) plus the rules
    # engine's verdicts from the listener phase — the round's evidence
    # that model-internals observability rides inside the train dispatch
    if health_res is not None and "health_stats_overhead_pct" in health_res:
        out["health_stats_overhead_pct"] = health_res[
            "health_stats_overhead_pct"]
        out["training_health"] = {
            "overhead_pct": health_res["health_stats_overhead_pct"],
            "syncs_outside_windows": health_res["syncs_outside_windows"],
            "listener_windows": health_res["listener_windows"],
            "listener_syncs": health_res["listener_syncs"],
            "report": health_res.get("training_health"),
        }

    # transformer flagship row: a SECOND named metric alongside the
    # ResNet headline (which keeps the vs_baseline trajectory unbroken);
    # same denominator convention — measured MFU ÷ the 40% north star
    if tlm_res is not None and "mfu" in tlm_res:
        out["transformer_lm_mfu"] = {
            "metric": "transformer_lm_mfu",
            "value": tlm_res["mfu"],
            "unit": "mfu",
            "vs_baseline": round(tlm_res["mfu"] / 0.40, 4),
            "tokens_per_sec": tlm_res["tokens_per_sec"],
            "model_flops_per_token": tlm_res["model_flops_per_token"],
        }

    if resnet_res is not None:
        out.update({
            "metric": "resnet50_train_throughput_per_chip",
            "value": resnet_res["examples_per_sec"],
            "unit": "examples/sec",
            "vs_baseline": round(resnet_res["mfu"] / 0.40, 4),
        })
    elif lenet_res is not None:
        out.update({
            "metric": "lenet_mnist_train_throughput",
            "value": lenet_res["examples_per_sec"],
            "unit": "examples/sec",
            "vs_baseline": round(lenet_res["mfu"] / 0.40, 4),
        })
    else:
        out.update({"metric": "bench_failed", "value": 0.0,
                    "unit": "examples/sec", "vs_baseline": 0.0})
    print(json.dumps(out))


if __name__ == "__main__":
    main()
