"""Benchmark: LeNet-5 MNIST training throughput on the real TPU chip.

BASELINE.md config #1 (LeNet-5 MNIST via the fit() API). Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}``.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
reported against the north-star instrumentation target: the ratio of measured
MFU to the 40% MFU goal (BASELINE.json). Extra keys carry the raw numbers.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _flops_per_example(conf, input_shape) -> float:
    """Analytic forward FLOPs for conv/dense layers (2*MACs); backward ≈ 2×
    forward, so a train step ≈ 3× forward FLOPs (standard MFU accounting)."""
    from deeplearning4j_tpu.nn.conf.layers import (
        ConvolutionLayer, DenseLayer, BaseOutputLayer)
    from deeplearning4j_tpu.nn.conf.inputs import InputType

    it = conf.input_type
    flops = 0.0
    h, w, c = (it.height, it.width, it.channels or 1)
    cur = InputType.convolutional(h, w, c)
    for layer in conf.layers:
        if isinstance(layer, ConvolutionLayer):
            out_t = layer.output_type(cur)
            kh, kw = layer.kernel_size
            macs = (out_t.height * out_t.width * layer.n_out
                    * kh * kw * (layer.n_in or c))
            flops += 2.0 * macs
            cur = out_t
        elif isinstance(layer, (DenseLayer, BaseOutputLayer)):
            flops += 2.0 * float(layer.n_in or 0) * float(layer.n_out or 0)
            if hasattr(layer, "output_type"):
                cur = layer.output_type(cur) if cur is not None else cur
        else:
            out_f = getattr(layer, "output_type", None)
            if out_f is not None:
                try:
                    cur = out_f(cur)
                except Exception:
                    pass
    return flops


def _peak_flops_per_sec() -> float:
    """Per-chip peak. TPU v5e: 197 TFLOP/s bf16 / 99 TF f32-ish via MXU.
    We report MFU against the bf16 peak (conservative)."""
    import jax
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v4" in kind:
        return 275e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v6" in kind:
        return 918e12
    return 197e12  # default to v5e


def main() -> None:
    import jax
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.datasets import MnistDataSetIterator
    from __graft_entry__ import _lenet_conf

    batch = 512
    conf = _lenet_conf()
    net = MultiLayerNetwork(conf).init()

    # stage K batches on device, train via the scan-fused path (ONE XLA
    # program per K steps — no per-step host dispatch; this is the framework's
    # idiomatic TPU inner loop, and it sidesteps the dev-tunnel RPC latency
    # that would otherwise dominate a per-step measurement)
    k = 8
    it = MnistDataSetIterator(batch, batch * k, seed=7, shuffle=False)
    xs = np.stack([np.asarray(d.features, np.float32) for d in it])
    ys = np.stack([np.asarray(d.labels, np.float32) for d in it])
    xs, ys = jax.device_put(xs), jax.device_put(ys)

    # warmup/compile
    jax.block_until_ready(net.fit_scan(xs, ys))

    rounds = 6
    t0 = time.perf_counter()
    for _ in range(rounds):
        losses = net.fit_scan(xs, ys)
    jax.block_until_ready(losses)
    dt = time.perf_counter() - t0

    steps = rounds * k
    examples_per_sec = steps * batch / dt
    train_flops_per_example = 3.0 * _flops_per_example(conf, (28, 28, 1))
    achieved = examples_per_sec * train_flops_per_example
    mfu = achieved / _peak_flops_per_sec()

    print(json.dumps({
        "metric": "lenet_mnist_train_throughput",
        "value": round(examples_per_sec, 1),
        "unit": "examples/sec",
        "vs_baseline": round(mfu / 0.40, 4),
        "mfu": round(mfu, 4),
        "step_ms": round(1000 * dt / steps, 3),
        "batch": batch,
        "flops_per_example_train": train_flops_per_example,
        "device": str(jax.devices()[0].device_kind),
        "final_score": float(losses[-1]),
    }))


if __name__ == "__main__":
    main()
