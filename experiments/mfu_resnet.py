"""ResNet-50 MFU experiment harness (round-4 continuation of PERF.md).

Runs bench-methodology measurements of the ResNet-50 train step under one
variation per invocation, selected by argv[1]:

  baseline      unroll=2 (shipping config)
  unroll4       lax.scan unroll=4
  unroll8       lax.scan unroll=8
  lhs           compiler_options latency-hiding-scheduler
  f32stats      (see bench note) nothing — placeholder for ablations

Usage: python experiments/mfu_resnet.py baseline unroll4 ...
"""

import os
import sys
import time

import numpy as np


def measure(tag, env=None, compiler_options=None, k=32, rounds=2):
    for key, val in (env or {}).items():
        os.environ[key] = val
    import jax
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import (_make_resnet, _stage_batches,
                       _resnet50_train_flops_per_example,
                       _peak_flops_per_sec)

    net, image, batch = _make_resnet()
    xs, ys = _stage_batches(1, batch, (image, image, 3), 1000, seed=11)
    x, y = jax.device_put(xs[0]), jax.device_put(ys[0])
    np.asarray(net.fit_repeated([x], [y], k))  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(rounds):
        losses = net.fit_repeated([x], [y], k)
    np.asarray(losses)
    dt = time.perf_counter() - t0
    steps = rounds * k
    step_ms = 1000 * dt / steps
    eps = steps * batch / dt
    mfu = eps * _resnet50_train_flops_per_example(image) / _peak_flops_per_sec()
    print(f"RESULT {tag}: step_ms={step_ms:.2f} mfu={mfu:.4f} "
          f"eps={eps:.1f}", flush=True)
    return step_ms, mfu


if __name__ == "__main__":
    tag = sys.argv[1] if len(sys.argv) > 1 else "baseline"
    env = {}
    if tag == "unroll4":
        env["DL4JTPU_SCAN_UNROLL"] = "4"
    elif tag == "unroll8":
        env["DL4JTPU_SCAN_UNROLL"] = "8"
    measure(tag, env=env)
